#!/usr/bin/env python
"""Benchmark driver: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Measures training throughput (examples/sec) the same way the reference's
benchmark harness does (reference: benchmark/fluid/fluid_benchmark.py:297-301
— num_samples/elapsed per pass) on the flagship config. Runs on whatever
device JAX_PLATFORMS selects (the real TPU chip under the driver).
"""

import json
import sys
import time

import numpy as np


def bench_mnist_mlp(batch=512, steps=50, warmup=10):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.framework import Program, program_guard

    main = Program()
    startup = Program()
    with program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[784], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=img, size=512, act="relu")
        h2 = fluid.layers.fc(input=h, size=512, act="relu")
        pred = fluid.layers.fc(input=h2, size=10, act=None)
        loss = fluid.layers.softmax_with_cross_entropy(logits=pred, label=label)
        avg_loss = fluid.layers.mean(loss)
        opt = fluid.optimizer.SGD(learning_rate=0.01)
        opt.minimize(avg_loss)

    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    x = rng.randn(batch, 784).astype(np.float32)
    y = rng.randint(0, 10, (batch, 1)).astype(np.int64)

    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(warmup):
            exe.run(main, feed={"img": x, "label": y}, fetch_list=[avg_loss])
        t0 = time.perf_counter()
        for _ in range(steps):
            (l,) = exe.run(main, feed={"img": x, "label": y},
                           fetch_list=[avg_loss])
        elapsed = time.perf_counter() - t0
    return batch * steps / elapsed


def main():
    try:
        ips = bench_mnist_mlp()
        print(json.dumps({
            "metric": "mnist_mlp_train_examples_per_sec",
            "value": round(float(ips), 2),
            "unit": "examples/sec",
            "vs_baseline": None,
        }))
    except Exception as e:  # never leave the driver without a JSON line
        print(json.dumps({
            "metric": "mnist_mlp_train_examples_per_sec",
            "value": 0.0,
            "unit": "examples/sec",
            "vs_baseline": None,
            "error": str(e)[:200],
        }))
        sys.exit(1)


if __name__ == "__main__":
    main()
