#!/usr/bin/env python
"""Benchmark driver: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Measures training throughput exactly the way the reference harness defines
it — examples/sec = num_samples / elapsed per pass (reference:
benchmark/fluid/fluid_benchmark.py:297-301) — on the flagship config.
Primary metric: ResNet-50 train images/sec on whatever device JAX selects
(the real TPU chip under the driver). Extra metrics (BERT-base samples/sec,
MNIST MLP examples/sec) ride along as additional keys. Select with
PADDLE_TPU_BENCH=resnet50|bert|mnist|all (default resnet50+mnist).
"""

import json
import os
import sys
import time

import numpy as np


def _throughput(run_step, batch, steps, warmup):
    """run_step must return a DEVICE array (return_numpy=False). Steps are
    dispatched asynchronously and the pipeline is drained once at the end —
    a per-step host read would serialize the device behind the host link
    (~100 ms round trip on a tunneled chip), which measures the tunnel, not
    the compute. Same accounting as the reference harness: examples/sec =
    num_samples / elapsed (benchmark/fluid/fluid_benchmark.py:297-301)."""
    import jax

    out = None
    for _ in range(warmup):
        out = run_step()
    jax.device_get(out)  # drain warmup (incl. compile) before timing
    t0 = time.perf_counter()
    for _ in range(steps):
        out = run_step()
    val = jax.device_get(out)  # drains the whole dispatched pipeline
    elapsed = time.perf_counter() - t0
    return batch * steps / elapsed, float(np.asarray(val).reshape(-1)[0])


def bench_mnist_mlp(batch=512, steps=50, warmup=10):
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    main, startup, h = models.mnist.get_model(lr=0.01)
    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    # pre-stage on device: an H2D transfer interleaved with in-flight
    # compute serializes the pipeline on a tunneled chip (measured ~200 ms
    # per transfer vs ~1 ms when the device is idle)
    x = jax.device_put(rng.randn(batch, 784).astype(np.float32))
    y = jax.device_put(
        rng.randint(0, 10, (batch, 1)).astype(np.int64))
    with fluid.scope_guard(scope):
        exe.run(startup)
        step = lambda: exe.run(main, feed={"img": x, "label": y},
                               fetch_list=[h["loss"]],
                               return_numpy=False)[0]
        ips, loss = _throughput(step, batch, steps, warmup)
    return ips


def bench_resnet50(batch=None, steps=20, warmup=5):
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    on_tpu = jax.default_backend() != "cpu"
    # batch 512 amortizes per-step host latency and fills the MXU (bf16)
    batch = batch or (512 if on_tpu else 4)
    main, startup, h = models.resnet.get_model(
        dataset="imagenet", depth=50, class_num=1000, lr=0.1)
    if os.environ.get("PADDLE_TPU_AMP", "1") != "0":
        fluid.contrib.mixed_precision.enable_bf16(main)
    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    # pre-stage the batch on device: measures the compute pipeline the way
    # the reference's double-buffered reader does (transfer overlapped),
    # not the host link
    x = jax.device_put(rng.randn(batch, 3, 224, 224).astype(np.float32))
    y = jax.device_put(
        rng.randint(0, 1000, (batch, 1)).astype(np.int64))
    with fluid.scope_guard(scope):
        exe.run(startup)
        step = lambda: exe.run(main, feed={"img": x, "label": y},
                               fetch_list=[h["loss"]],
                               return_numpy=False)[0]
        ips, loss = _throughput(step, batch, steps, warmup)
    assert np.isfinite(loss)
    return ips


def bench_bert_base(batch=None, steps=10, warmup=3, seq_len=128):
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    on_tpu = jax.default_backend() != "cpu"
    batch = batch or (64 if on_tpu else 2)
    if not on_tpu:
        kwargs = dict(d_model=128, n_layers=2, n_heads=2, d_inner=256)
    else:
        kwargs = dict(d_model=768, n_layers=12, n_heads=12, d_inner=3072)
    main, startup, h = models.bert.get_model(
        batch_size=batch, seq_len=seq_len, vocab_size=30522, dropout=0.1,
        lr=1e-4, max_position=max(512, seq_len), **kwargs)
    if os.environ.get("PADDLE_TPU_AMP", "1") != "0":
        fluid.contrib.mixed_precision.enable_bf16(main)
    b = models.bert.make_fake_batch(batch, seq_len, 30522,
                                    kwargs["n_heads"])
    b = {k: jax.device_put(v) for k, v in b.items()}
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        step = lambda: exe.run(main, feed=b, fetch_list=[h["loss"]],
                               return_numpy=False)[0]
        sps, loss = _throughput(step, batch, steps, warmup)
    assert np.isfinite(loss)
    return sps


def bench_bert_long(batch=4, seq_len=2048, steps=5, warmup=2):
    """BERT-base at 2048-token context through the flash-attention path —
    long-context training at O(T) attention memory (the unfused
    composition needs 12 x [B, H, 2048, 2048] score tensors and must
    rematerialize to survive). TPU only, like the flash micro-bench."""
    import jax

    if jax.default_backend() == "cpu":
        raise RuntimeError("bert_long bench requires the TPU backend")
    return bench_bert_base(batch=batch, steps=steps, warmup=warmup,
                           seq_len=seq_len)


def bench_flash_attention(seq=2048, batch=4, heads=16, dim=64, iters=20):
    """Pallas flash fwd+bwd vs XLA-recompute backward at seq 2048 — the
    attention-training kernel win (TPU only; interpret mode would measure
    the emulator)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.kernels.flash_attention import (_xla_attention,
                                                    flash_attention)

    if jax.default_backend() == "cpu":
        raise RuntimeError("flash bench requires the TPU backend")
    rng = np.random.RandomState(0)
    q = jax.device_put(jnp.asarray(
        rng.randn(batch, heads, seq, dim), jnp.bfloat16))
    k = jax.device_put(jnp.asarray(
        rng.randn(batch, heads, seq, dim), jnp.bfloat16))
    v = jax.device_put(jnp.asarray(
        rng.randn(batch, heads, seq, dim), jnp.bfloat16))

    from paddle_tpu.kernels.flash_attention import pick_block

    bq = pick_block(seq)
    flash_g = jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(flash_attention(
            a, b, c, None, 0, True, None, 0.0, bq, bq,
            False).astype(jnp.float32)),
        argnums=(0, 1, 2)))
    xla_g = jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(_xla_attention(
            a, b, c, True, dim ** -0.5).astype(jnp.float32)),
        argnums=(0, 1, 2)))

    def time_fn(fn):
        jax.device_get(fn(q, k, v))  # compile + warm
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(q, k, v)
        jax.device_get(out)
        return (time.perf_counter() - t0) / iters

    t_flash = time_fn(flash_g)
    t_xla = time_fn(xla_g)
    return {"flash_attn_bwd_ms_seq2048": round(t_flash * 1e3, 3),
            "xla_recompute_bwd_ms_seq2048": round(t_xla * 1e3, 3),
            "flash_attn_bwd_speedup": round(t_xla / t_flash, 3)}


def main():
    which = os.environ.get("PADDLE_TPU_BENCH", "default")
    result = {
        "metric": "resnet50_train_images_per_sec",
        "value": 0.0,
        "unit": "images/sec",
        "vs_baseline": None,  # reference publishes no absolute throughput
    }
    errors = {}

    def _try(name, fn):
        try:
            return round(float(fn()), 2)
        except Exception as e:  # noqa: BLE001
            errors[name] = str(e)[:200]
            return None

    if which in ("default", "all", "resnet50"):
        v = _try("resnet50", bench_resnet50)
        if v:
            result["value"] = v
    if which in ("default", "all", "bert"):
        v = _try("bert", bench_bert_base)
        if v:
            result["bert_base_samples_per_sec"] = v
        v = _try("bert_long", bench_bert_long)
        if v:
            result["bert_seq2048_samples_per_sec"] = v
    if which in ("default", "all", "flash"):
        try:
            result.update(bench_flash_attention())
        except Exception as e:  # noqa: BLE001
            errors["flash"] = str(e)[:200]
    if which in ("default", "all", "mnist") or result["value"] == 0.0:
        v = _try("mnist", bench_mnist_mlp)
        if v:
            result["mnist_mlp_examples_per_sec"] = v
            if result["value"] == 0.0:
                result["metric"] = "mnist_mlp_train_examples_per_sec"
                result["unit"] = "examples/sec"
                result["value"] = v
    if errors:
        result["errors"] = errors
    print(json.dumps(result))
    if result["value"] == 0.0:
        sys.exit(1)


if __name__ == "__main__":
    main()
