#!/usr/bin/env python
"""Benchmark driver: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Measures training throughput exactly the way the reference harness defines
it — examples/sec = num_samples / elapsed per pass (reference:
benchmark/fluid/fluid_benchmark.py:297-301) — on the flagship config.
Primary metric: ResNet-50 train images/sec on whatever device JAX selects
(the real TPU chip under the driver). Extra metrics (BERT-base + seq-2048
samples/sec, Transformer-NMT samples/sec, DeepFM examples/sec, the flash
microbench, and a diagnostic MNIST number) ride along as additional keys —
all five BASELINE.md configs appear. Select with
PADDLE_TPU_BENCH=resnet50|bert|transformer|deepfm|flash|mnist|memory|multichip|serving|pipeline|layout|all
(default: everything except multichip — the multi-device GSPMD scaling
sweep, see bench_multichip — serving — the INT8 freeze/quantize/
continuous-batching pipeline, see bench_serving — pipeline — the
async-dispatch / prefetch / async-checkpoint block, see bench_pipeline —
and layout — the NCHW-vs-NHWC layout-pass A/B, see bench_layout).
"""

import json
import os
import sys
import time

import numpy as np


def _throughput(run_step, batch, steps, warmup):
    """run_step must return a DEVICE array (return_numpy=False). Steps are
    dispatched asynchronously and the pipeline is drained once at the end —
    a per-step host read would serialize the device behind the host link
    (~100 ms round trip on a tunneled chip), which measures the tunnel, not
    the compute. Same accounting as the reference harness: examples/sec =
    num_samples / elapsed (benchmark/fluid/fluid_benchmark.py:297-301)."""
    import jax

    out = None
    for _ in range(warmup):
        out = run_step()
    jax.device_get(out)  # drain warmup (incl. compile) before timing
    t0 = time.perf_counter()
    for _ in range(steps):
        out = run_step()
    val = jax.device_get(out)  # drains the whole dispatched pipeline
    elapsed = time.perf_counter() - t0
    return batch * steps / elapsed, float(np.asarray(val).reshape(-1)[0])


def bench_mnist_mlp(batch=512, steps=50, warmup=10, reps=5):
    """Median of ``reps`` timed windows: a 2-layer MLP step is ~pure
    dispatch overhead on a tunneled chip, so a single window swings 2x+
    with tunnel latency (VERDICT r3 Weak #7) — the median is the number
    that means anything."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    main, startup, h = models.mnist.get_model(lr=0.01)
    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    # pre-stage on device: an H2D transfer interleaved with in-flight
    # compute serializes the pipeline on a tunneled chip (measured ~200 ms
    # per transfer vs ~1 ms when the device is idle)
    x = jax.device_put(rng.randn(batch, 784).astype(np.float32))
    y = jax.device_put(
        rng.randint(0, 10, (batch, 1)).astype(np.int64))
    with fluid.scope_guard(scope):
        exe.run(startup)
        step = lambda: exe.run(main, feed={"img": x, "label": y},
                               fetch_list=[h["loss"]],
                               return_numpy=False)[0]
        vals = [_throughput(step, batch, steps, warmup)[0]
                for _ in range(reps)]
    return float(np.median(vals))


def bench_resnet50(batch=None, steps=30, warmup=5):
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    on_tpu = jax.default_backend() != "cpu"
    # batch 512 amortizes per-step host latency and fills the MXU (bf16)
    batch = batch or (512 if on_tpu else 4)
    main, startup, h = models.resnet.get_model(
        dataset="imagenet", depth=50, class_num=1000, lr=0.1)
    if os.environ.get("PADDLE_TPU_AMP", "1") != "0":
        fluid.contrib.mixed_precision.enable_bf16(main)
    exe = fluid.Executor()
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    # pre-stage the batch on device: measures the compute pipeline the way
    # the reference's double-buffered reader does (transfer overlapped),
    # not the host link
    x = jax.device_put(rng.randn(batch, 3, 224, 224).astype(np.float32))
    y = jax.device_put(
        rng.randint(0, 1000, (batch, 1)).astype(np.int64))
    with fluid.scope_guard(scope):
        exe.run(startup)
        step = lambda: exe.run(main, feed={"img": x, "label": y},
                               fetch_list=[h["loss"]],
                               return_numpy=False)[0]
        ips, loss = _throughput(step, batch, steps, warmup)
    assert np.isfinite(loss)
    return ips


def bench_bert_base(batch=None, steps=30, warmup=4, seq_len=128):
    """steps=30: at ~60ms/step the timed window must dwarf the tunnel's
    session-variable readback overhead (~0.3-2s) or the number measures
    the session, not the model (observed 730 vs 1150 samples/s for the
    same build across sessions at steps=10)."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    on_tpu = jax.default_backend() != "cpu"
    batch = batch or (64 if on_tpu else 2)
    if not on_tpu:
        kwargs = dict(d_model=128, n_layers=2, n_heads=2, d_inner=256)
    else:
        kwargs = dict(d_model=768, n_layers=12, n_heads=12, d_inner=3072)
    main, startup, h = models.bert.get_model(
        batch_size=batch, seq_len=seq_len, vocab_size=30522, dropout=0.1,
        lr=1e-4, max_position=max(512, seq_len), **kwargs)
    if os.environ.get("PADDLE_TPU_AMP", "1") != "0":
        fluid.contrib.mixed_precision.enable_bf16(main)
    b = models.bert.make_fake_batch(batch, seq_len, 30522,
                                    kwargs["n_heads"])
    b = {k: jax.device_put(v) for k, v in b.items()}
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        step = lambda: exe.run(main, feed=b, fetch_list=[h["loss"]],
                               return_numpy=False)[0]
        sps, loss = _throughput(step, batch, steps, warmup)
    assert np.isfinite(loss)
    return sps


def bench_bert_long(batch=4, seq_len=2048, steps=12, warmup=3):
    """BERT-base at 2048-token context through the flash-attention path —
    long-context training at O(T) attention memory (the unfused
    composition needs 12 x [B, H, 2048, 2048] score tensors and must
    rematerialize to survive). TPU only, like the flash micro-bench."""
    import jax

    if jax.default_backend() == "cpu":
        raise RuntimeError("bert_long bench requires the TPU backend")
    return bench_bert_base(batch=batch, steps=steps, warmup=warmup,
                           seq_len=seq_len)




def _pipelined_throughput(main, startup, h_loss, feed_vars, reader_fn,
                          batch, steps, warmup, transforms=None):
    """Train THROUGH the host->device input pipeline: a producer thread
    pushes host batches into the native blocking queue (PyReader), the
    step loop stages batch i+1 onto the device (async device_put) while
    step i computes — the reference's double-buffered reader discipline
    (operators/reader/buffered_reader.cc:15: one buffer transfers while
    the previous computes) instead of bench-side pre-staged arrays.
    ``transforms`` maps feed names to on-device jitted post-transfer
    functions (e.g. uint8 -> normalized float32, the wire-width fix)."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu.layers.io import PyReader

    reader = PyReader(feed_vars, capacity=4)
    reader.decorate_paddle_reader(reader_fn)
    exe = fluid.Executor()
    scope = fluid.Scope()
    transforms = transforms or {}

    def stage(d):
        out = {}
        for k, v in d.items():
            v = jax.device_put(v)
            if k in transforms:
                v = transforms[k](v)
            out[k] = v
        return out
    with fluid.scope_guard(scope):
        exe.run(startup)
        reader.start()
        cur = stage(reader.next_feed())
        out = None
        for _ in range(warmup):
            nxt = stage(reader.next_feed())   # H2D overlaps the step below
            out = exe.run(main, feed=cur, fetch_list=[h_loss],
                          return_numpy=False)[0]
            cur = nxt
        jax.device_get(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            nxt = stage(reader.next_feed())
            out = exe.run(main, feed=cur, fetch_list=[h_loss],
                          return_numpy=False)[0]
            cur = nxt
        val = jax.device_get(out)
        elapsed = time.perf_counter() - t0
    assert np.isfinite(float(np.asarray(val).reshape(-1)[0]))
    return batch * steps / elapsed


def bench_resnet50_pipelined(batch=None, steps=None, warmup=2,
                             wire_dtype="float32"):
    """ResNet-50 fed from HOST memory through PyReader + device staging
    (VERDICT r4 Next #2). ``wire_dtype="float32"`` moves images at full
    width, the traffic the reference's reader chain moves (~300 MB/batch
    at 512); ``"uint8"`` is the wire-width fix — raw bytes over the link,
    normalization on device (4x less transfer). On the TUNNELED bench
    chip either is link-bound (~24 MB/s effective H2D measured round 5 —
    the tunnel, not the pipeline: BERT's KB-scale feeds pipeline at ~2%
    overhead), so steps default low to bound driver bench runtime; on a
    co-located host (the deployment scenario, PCIe-class link) the same
    path hides a 308 MB batch under the 213 ms step."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    on_tpu = jax.default_backend() != "cpu"
    batch = batch or (512 if on_tpu else 4)
    steps = steps or (6 if on_tpu else 3)
    main, startup, h = models.resnet.get_model(
        dataset="imagenet", depth=50, class_num=1000, lr=0.1)
    if os.environ.get("PADDLE_TPU_AMP", "1") != "0":
        fluid.contrib.mixed_precision.enable_bf16(main)
    rng = np.random.RandomState(0)
    # rotating pool of distinct host buffers: every step moves a real
    # fresh batch over the link without holding `steps` batches in RAM
    img_wire = h["img"]
    if wire_dtype == "uint8":
        imgs = [rng.randint(0, 256, (batch, 3, 224, 224)).astype(np.uint8)
                for _ in range(3)]
        transforms = {h["img"].name: jax.jit(
            lambda u: u.astype(jnp.float32) / 127.5 - 1.0)}

        class _WireVar:  # img var with the WIRE dtype (bytes over the
            name = h["img"].name  # link; PyReader casts to var dtype)
            dtype = "uint8"

        img_wire = _WireVar()
    else:
        imgs = [rng.randn(batch, 3, 224, 224).astype(np.float32)
                for _ in range(3)]
        transforms = None
    pool = [(im, rng.randint(0, 1000, (batch, 1)).astype(np.int64))
            for im in imgs]
    total = warmup + steps + 2
    return _pipelined_throughput(
        main, startup, h["loss"], [img_wire, h["label"]],
        lambda: (pool[i % len(pool)] for i in range(total)),
        batch, steps, warmup, transforms=transforms)


def bench_bert_pipelined(batch=None, steps=30, warmup=4, seq_len=128):
    """BERT-base fed through the same pipeline (token ids are ~KB-scale,
    so this isolates the per-step pipeline overhead from bandwidth)."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    on_tpu = jax.default_backend() != "cpu"
    batch = batch or (64 if on_tpu else 2)
    if not on_tpu:
        kwargs = dict(d_model=128, n_layers=2, n_heads=2, d_inner=256)
    else:
        kwargs = dict(d_model=768, n_layers=12, n_heads=12, d_inner=3072)
    main, startup, h = models.bert.get_model(
        batch_size=batch, seq_len=seq_len, vocab_size=30522, dropout=0.1,
        lr=1e-4, max_position=max(512, seq_len), **kwargs)
    if os.environ.get("PADDLE_TPU_AMP", "1") != "0":
        fluid.contrib.mixed_precision.enable_bf16(main)
    b = models.bert.make_fake_batch(batch, seq_len, 30522,
                                    kwargs["n_heads"])
    feeds = h["feeds"]
    names = sorted(b)
    total = warmup + steps + 2
    return _pipelined_throughput(
        main, startup, h["loss"], [feeds[n] for n in names],
        lambda: (tuple(b[n] for n in names) for _ in range(total)),
        batch, steps, warmup)


def bench_transformer_nmt(batch=None, steps=40, warmup=4, seq_len=256):
    """Transformer NMT (encoder-decoder, label-smoothed CE) — BASELINE.md
    north-star config #4 (reference benchmark model:
    benchmark/fluid/models/machine_translation.py). Transformer-base
    geometry; variable-length capability is carried by the per-sequence
    length feeds (key-padding masks), bench feeds run full-length.
    steps=40 keeps the timed window ~2 s — a 20-step (~1 s) window
    swung 538-648 samples/s across sessions on the tunneled chip."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    on_tpu = jax.default_backend() != "cpu"
    batch = batch or (32 if on_tpu else 2)
    if on_tpu:
        kwargs = dict(d_model=512, n_heads=8, d_inner=2048, n_layers=6,
                      vocab_size=32768)
    else:
        kwargs = dict(d_model=64, n_heads=2, d_inner=128, n_layers=2,
                      vocab_size=512)
    main, startup, h = models.transformer.get_model(
        batch_size=batch, seq_len=seq_len, dropout=0.1, lr=1e-4,
        **kwargs)
    if os.environ.get("PADDLE_TPU_AMP", "1") != "0":
        fluid.contrib.mixed_precision.enable_bf16(main)
    b = models.transformer.make_fake_batch(batch, seq_len,
                                           kwargs["vocab_size"])
    b = {k: jax.device_put(v) for k, v in b.items()}
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        step = lambda: exe.run(main, feed=b, fetch_list=[h["loss"]],
                               return_numpy=False)[0]
        sps, loss = _throughput(step, batch, steps, warmup)
    assert np.isfinite(loss)
    return sps


def bench_deepfm(batch=None, steps=30, warmup=5):
    """DeepFM CTR — BASELINE.md north-star config #5 (reference:
    tests/unittests/dist_ctr.py sparse-embedding training). Criteo-like
    geometry: 39 fields over a 1M-id space, 16-dim embeddings, 400-wide
    DNN tower; large batch as CTR training runs it."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    on_tpu = jax.default_backend() != "cpu"
    batch = batch or (2048 if on_tpu else 64)
    num_features, num_fields = (1000000, 39) if on_tpu else (1000, 5)
    main, startup, h = models.deepfm.get_model(
        batch_size=batch, num_features=num_features, num_fields=num_fields,
        embed_dim=16, lr=1e-3)
    b = models.deepfm.make_fake_batch(batch, num_features, num_fields)
    b = {k: jax.device_put(v) for k, v in b.items()}
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        step = lambda: exe.run(main, feed=b, fetch_list=[h["loss"]],
                               return_numpy=False)[0]
        eps, loss = _throughput(step, batch, steps, warmup)
    assert np.isfinite(loss)
    return eps


def bench_flash_attention(seq=2048, batch=4, heads=16, dim=64, iters=30,
                          reps=7):
    """Pallas flash fwd+bwd vs XLA-recompute backward at seq 2048 — the
    attention-training kernel win (TPU only; interpret mode would measure
    the emulator).

    Variance-robust protocol (VERDICT r3 Next #1). Two confounds sank the
    previous protocols on the tunneled chip: a per-call overhead of
    ~1-2.5s (dispatch + result readback over the tunnel) that dwarfs the
    ~2-12ms kernels, and its session-to-session drift. Both cancel by
    measuring the MARGINAL cost: each path runs as a lax.fori_loop of
    fwd+bwd steps chained by a data dependency, timed at two loop counts
    (``n_lo``/``n_hi``); per-step device time = (T_hi - T_lo)/Δn, with
    the fixed overhead subtracting out. All four variants are timed
    INTERLEAVED across ``reps`` rounds. The headline ``*_ms`` and
    ``_speedup`` keys use diff-of-medians (median wall per loop count,
    then difference — one outlier window cannot skew it); the per-rep
    paired marginals feed the ``_min``/``_spread``/``_speedup_min``/
    ``_speedup_max`` keys so the JSON carries its own error bars.
    Calibration on this setup: a lone 4096^3 matmul dispatch reads
    ~146ms/iter wall but ~3ms/iter marginal — single-shot timing
    measures the tunnel, not the chip."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.kernels.flash_attention import (_xla_attention,
                                                    flash_attention,
                                                    pick_block)

    # Δn must make the signal (Δn x kernel time) dwarf the overhead
    # jitter (~±0.5s) PER PATH: the ~2.5ms flash kernel needs ~4x the
    # loop length of the ~12ms xla recompute for the same signal.
    # iters=30 (~12-15s per hi window) puts the per-window jitter at
    # ~4% of the signal so the published spread target
    # (spread <= 0.3 x median, VERDICT r4 Next #9) is achievable —
    # round 4's ~5s windows left the per-rep marginals with a ±50% band
    n_lo = 8
    n_hi = {"flash": n_lo + iters * 160, "xla": n_lo + iters * 40}
    if jax.default_backend() == "cpu":
        raise RuntimeError("flash bench requires the TPU backend")
    rng = np.random.RandomState(0)
    q = jax.device_put(jnp.asarray(
        rng.randn(batch, heads, seq, dim), jnp.bfloat16))
    k = jax.device_put(jnp.asarray(
        rng.randn(batch, heads, seq, dim), jnp.bfloat16))
    v = jax.device_put(jnp.asarray(
        rng.randn(batch, heads, seq, dim), jnp.bfloat16))

    bq = pick_block(seq)
    flash_g = jax.grad(
        lambda a, b, c: jnp.sum(flash_attention(
            a, b, c, None, 0, True, None, 0.0, bq, bq,
            False).astype(jnp.float32)),
        argnums=(0, 1, 2))
    xla_g = jax.grad(
        lambda a, b, c: jnp.sum(_xla_attention(
            a, b, c, True, dim ** -0.5).astype(jnp.float32)),
        argnums=(0, 1, 2))

    from tools.marginal_timing import (chained_grad_loop,
                                       run_marginal_protocol)

    variants = {
        path: (chained_grad_loop(g, n_lo), n_lo,
               chained_grad_loop(g, n_hi[path]), n_hi[path])
        for path, g in (("flash", flash_g), ("xla", xla_g))}
    # warmup_rounds=2: BENCH_r05 showed the single untimed interleaved
    # round still let a 65.5ms straggler land in a timed rep (speedup_min
    # 0.199 against a 3.4ms median) — the second round absorbs it
    measured = run_marginal_protocol(variants, (q, k, v), reps,
                                     warmup_rounds=2)
    (med_flash, t_flash), (med_xla, t_xla) = (measured["flash"],
                                              measured["xla"])
    if med_flash <= 0 or med_xla <= 0:
        # even the medians drowned in overhead jitter — no number from
        # this session is trustworthy; better an errors entry than a
        # garbage headline
        raise RuntimeError(
            "marginal timing non-positive (flash %.4fs, xla %.4fs): "
            "tunnel overhead swamped the signal" % (med_flash, med_xla))
    # a rep whose marginal is non-positive, far below, OR far above the
    # headline median caught an overhead swing bigger than its signal; it
    # carries no kernel information — exclude it from ALL per-rep
    # statistics (ratios AND error bars). The low cut stops an
    # epsilon-positive rep publishing an absurd speedup_max; the
    # symmetric high cut stops one straggler-contaminated window
    # publishing an absurd spread/speedup_min (the 65.5ms-vs-3.4ms rep
    # in BENCH_r05).
    lo_f, lo_x = 0.25 * med_flash, 0.25 * med_xla
    hi_f, hi_x = 4.0 * med_flash, 4.0 * med_xla
    t_flash_ok = [t for t in t_flash if lo_f < t < hi_f]
    t_xla_ok = [t for t in t_xla if lo_x < t < hi_x]
    ratios = sorted(x / f for f, x in zip(t_flash, t_xla)
                    if lo_f < f < hi_f and lo_x < x < hi_x)
    ms = lambda s: round(float(s) * 1e3, 3)
    out = {
        "flash_attn_bwd_ms_seq2048": ms(med_flash),
        "xla_recompute_bwd_ms_seq2048": ms(med_xla),
        "flash_attn_bwd_speedup": round(med_xla / med_flash, 3),
        "flash_attn_bwd_reps": reps,
        "flash_attn_bwd_reps_clean": len(ratios),
    }
    if t_flash_ok:
        out["flash_attn_bwd_ms_min"] = ms(min(t_flash_ok))
        out["flash_attn_bwd_ms_spread"] = ms(
            max(t_flash_ok) - min(t_flash_ok))
    if t_xla_ok:
        out["xla_recompute_bwd_ms_min"] = ms(min(t_xla_ok))
        out["xla_recompute_bwd_ms_spread"] = ms(
            max(t_xla_ok) - min(t_xla_ok))
    if ratios:
        out["flash_attn_bwd_speedup_min"] = round(ratios[0], 3)
        out["flash_attn_bwd_speedup_max"] = round(ratios[-1], 3)
    return out


def bench_multichip(device_counts=(1, 2, 4, 8), steps=12, warmup=3):
    """Weak-scaling sweep over dp mesh sizes through the GSPMD engine
    path (Executor.run(mesh=...) → mesh-keyed jit, psum gradient
    reduction derived by the partitioner — no pserver round-trip).

    With >=2 real devices: run ResNet-50 and BERT-base in-process over
    dp meshes on the first 1/2/4/8 devices (weak scaling: global batch =
    per-device batch × n, so perfect scaling is flat step time and n×
    throughput). With a single real device (the usual tunneled bench
    chip), fall back to tools/multichip_probe.py — per-count
    subprocesses on forced-host CPU devices; that measures partitioning
    overhead rather than ICI, but still catches any scaling break in the
    compiled graph (unsharded fallbacks, per-step host gathers).

    Emits ``resnet50_dp{n}_images_per_sec`` / ``bert_dp{n}_samples_per_sec``
    per count plus ``*_scaling_efficiency`` at the largest N measured —
    tput(N)/(N × tput(1)) on real devices; on the virtual-CPU fallback
    (flagged by ``multichip_virtual_cpu_devices``) the probe's
    shared-capacity normalization tput(N)/tput(1), since N forced-host
    devices split one physical CPU and can never show N×.

    The replicated-vs-sharded A/B: each model re-runs at the largest N
    with the ZeRO-1 sharded weight update on
    (``*_zero1_dp{n}_*`` / ``*_zero1_scaling_efficiency``), sweeps the
    gradient-reduce bucket size under it
    (``*_overlap_bucket{B}mb_dp{n}_*``), and reports the optimizer-state
    bytes the sharded update reclaims per device
    (``*_zero1_savings_bytes``, from the static SPMD ledger)."""
    import jax

    from paddle_tpu import flags

    out = {}
    n_real = len(jax.devices())
    counts = [n for n in device_counts if n <= n_real]
    bucket_sweep_mb = (1, 8)
    if len(counts) >= 2:
        import paddle_tpu.fluid as fluid
        from paddle_tpu import models
        from paddle_tpu.analysis.spmd import analyze_spmd
        from paddle_tpu.parallel import ShardingRules, make_mesh

        on_tpu = jax.default_backend() != "cpu"
        rng = np.random.RandomState(0)
        jobs = {}
        per_img = 128 if on_tpu else 4

        def resnet(batch):
            main, startup, h = models.resnet.get_model(
                dataset="imagenet", depth=50, class_num=1000, lr=0.1)
            if os.environ.get("PADDLE_TPU_AMP", "1") != "0":
                fluid.contrib.mixed_precision.enable_bf16(main)
            feed = {"img": rng.randn(batch, 3, 224, 224).astype(np.float32),
                    "label": rng.randint(0, 1000,
                                         (batch, 1)).astype(np.int64)}
            return main, startup, h["loss"], feed

        jobs["resnet50"] = (per_img, "images_per_sec", resnet)
        per_bert = 32 if on_tpu else 2

        def bert(batch):
            kw = (dict(d_model=768, n_layers=12, n_heads=12, d_inner=3072)
                  if on_tpu else
                  dict(d_model=128, n_layers=2, n_heads=2, d_inner=256))
            main, startup, h = models.bert.get_model(
                batch_size=batch, seq_len=128, vocab_size=30522,
                dropout=0.1, lr=1e-4, max_position=512, **kw)
            if os.environ.get("PADDLE_TPU_AMP", "1") != "0":
                fluid.contrib.mixed_precision.enable_bf16(main)
            feed = models.bert.make_fake_batch(batch, 128, 30522,
                                               kw["n_heads"])
            return main, startup, h["loss"], feed

        jobs["bert"] = (per_bert, "samples_per_sec", bert)

        def measure(build, batch, n):
            main, startup, loss, feed = build(batch)
            mesh = make_mesh({"dp": n}, devices=jax.devices()[:n])
            feed = {k: jax.device_put(v) for k, v in feed.items()}
            exe = fluid.Executor()
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(startup)
                step = lambda: exe.run(
                    main, feed=feed, fetch_list=[loss], mesh=mesh,
                    shard_rules=ShardingRules(),
                    return_numpy=False)[0]
                tput, lv = _throughput(step, batch, steps, warmup)
            assert np.isfinite(lv)
            return tput, main, feed

        for name, (per_dev, unit, build) in jobs.items():
            tputs = {}
            for n in counts:
                tput, _, _ = measure(build, per_dev * n, n)
                tputs[n] = tput
                out["%s_dp%d_%s" % (name, n, unit)] = round(tput, 2)
            top = max(tputs)
            out["%s_scaling_efficiency" % name] = round(
                tputs[top] / (top * tputs[1]), 4)
            # the A/B: sharded update (+ bucket sweep) at the top count
            flags.set_flags({"zero": True})
            try:
                ztput, main, feed = measure(build, per_dev * top, top)
                out["%s_zero1_dp%d_%s" % (name, top, unit)] = round(
                    ztput, 2)
                out["%s_zero1_scaling_efficiency" % name] = round(
                    ztput / (top * tputs[1]), 4)
                for b in bucket_sweep_mb:
                    flags.set_flags({"grad_bucket_mb": float(b)})
                    btput, _, _ = measure(build, per_dev * top, top)
                    out["%s_overlap_bucket%dmb_dp%d_%s"
                        % (name, b, top, unit)] = round(btput, 2)
            finally:
                flags.reset_flag("zero")
                flags.reset_flag("grad_bucket_mb")
            base_rep = analyze_spmd(
                main.desc, mesh={"dp": top},
                shard_rules=ShardingRules(),
                feed_shapes={k: tuple(np.asarray(v).shape)
                             for k, v in feed.items()})
            out["%s_zero1_savings_bytes" % name] = \
                base_rep.opt_state.zero1_savings_bytes
    else:
        # single-chip host: forced-host-device CPU probe in subprocesses
        from paddle_tpu.analysis.spmd import analyze_spmd
        from paddle_tpu.parallel import ShardingRules
        from tools.multichip_probe import (_build, efficiency_table,
                                           probe_scaling)

        for name, model, unit in (("resnet50", "resnet50",
                                   "images_per_sec"),
                                  ("bert", "bert", "samples_per_sec")):
            rows = efficiency_table(probe_scaling(
                model=model, devices=tuple(device_counts),
                batch_per_device=8, steps=steps, warmup=warmup))
            for n, t, _ in rows:
                out["%s_dp%d_%s" % (name, n, unit)] = round(t, 2)
            out["%s_scaling_efficiency" % name] = round(rows[-1][2], 4)
            # the A/B at the largest count: sharded update + one
            # bucketed run, normalized against the replicated tput(1)
            top = rows[-1][0]
            base1 = rows[0][1]
            ztput = probe_scaling(
                model=model, devices=(top,), batch_per_device=8,
                steps=steps, warmup=warmup, zero1=True)[top]
            out["%s_zero1_dp%d_%s" % (name, top, unit)] = round(
                ztput, 2)
            out["%s_zero1_scaling_efficiency" % name] = round(
                ztput / base1, 4) if base1 else None
            for b in bucket_sweep_mb:
                btput = probe_scaling(
                    model=model, devices=(top,), batch_per_device=8,
                    steps=steps, warmup=warmup, zero1=True,
                    bucket_mb=float(b))[top]
                out["%s_overlap_bucket%dmb_dp%d_%s"
                    % (name, b, top, unit)] = round(btput, 2)
            main, _, _, feed = _build(model, 8 * top)
            base_rep = analyze_spmd(
                main.desc, mesh={"dp": top},
                shard_rules=ShardingRules(),
                feed_shapes={k: tuple(np.asarray(v).shape)
                             for k, v in feed.items()})
            out["%s_zero1_savings_bytes" % name] = \
                base_rep.opt_state.zero1_savings_bytes
        out["multichip_virtual_cpu_devices"] = 1
    out["multichip_device_counts"] = list(counts if len(counts) >= 2
                                          else device_counts)
    return out


def bench_trace_opt(seq_len=128, batch=2):
    """Trace/compile-time effect of the desc-level transform pipeline
    (analysis/transforms.py): builds a small *unfused* BERT training
    program — the composition the fuse-attention pass targets — and
    reports op counts plus wall time to first compiled step at opt level
    0 vs 2. Runs on whatever backend is up (the metric is trace-side, so
    CPU numbers are meaningful too)."""
    import time

    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import flags, models
    from paddle_tpu.analysis import optimize_program

    main, startup, h = models.bert.get_model(
        batch_size=batch, seq_len=seq_len, vocab_size=1000, dropout=0.0,
        lr=1e-4, max_position=max(512, seq_len), d_model=128, n_layers=2,
        n_heads=2, d_inner=256, use_fused_attention=False)
    fetch = [h["loss"]]
    feeds = list(models.bert.make_fake_batch(batch, seq_len, 1000, 2))
    n_ops0 = len(main.desc.block(0).ops)
    opt_desc, report = optimize_program(
        main.desc, level=2, feed_names=feeds, fetch_names=[h["loss"].name])
    out = {
        "bert_unfused_ops_opt0": n_ops0,
        "bert_unfused_ops_opt2": len(opt_desc.block(0).ops),
        "opt2_rewrites": report.total,
        "opt2_attention_rewrites": report.rewrites.get("fuse-attention", 0),
    }
    b = models.bert.make_fake_batch(batch, seq_len, 1000, 2)
    b = {k: jax.device_put(v) for k, v in b.items()}
    for level, key in ((0, "compile_ms_opt0"), (2, "compile_ms_opt2")):
        flags.set_flags({"opt_level": level})
        try:
            exe = fluid.Executor()
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(startup)
                t0 = time.perf_counter()
                exe.run(main, feed=b, fetch_list=fetch)
                out[key] = round((time.perf_counter() - t0) * 1e3, 1)
        finally:
            flags.reset_flag("opt_level")
    return out


def bench_memory_planning(seq_len=2048):
    """Memory-planning trajectory metrics (PADDLE_TPU_OPT_LEVEL=3,
    analysis/memory.py):

    * ``bert_seq2048_max_batch`` — the largest batch whose opt-3
      compiled BERT training step fits the HBM budget
      (device limit x PADDLE_TPU_HBM_BUDGET_FRAC; a nominal 16 GiB chip
      when the backend reports no allocator limit, e.g. CPU). Found by
      doubling + bisection over ``cost_analysis`` compile-peaks — the
      executable is compiled but never run, so an over-budget candidate
      cannot OOM the bench.
    * ``{bert_seq2048,resnet50}_peak_hbm_bytes_opt{2,3}`` — XLA's
      compile-peak (args + outputs - donated aliases + temps) for the
      same training step at opt 2 vs opt 3, with the device limit pinned
      tight (60% of the opt-2 peak and of the planner's own liveness
      estimate) so the budget forces auto-remat: opt 3 landing below
      opt 2 is the watermark drop the plan predicts. The
      ``*_plan_predicted_peak_bytes`` keys carry the planner's own
      model-space estimate for the opt-3 executable. Caveat for CPU
      rounds: the XLA CPU backend schedules without memory awareness —
      a 20-matmul-chain probe shows ``jax.checkpoint`` leaves its
      compile-peak unchanged (320 -> 352 MiB temp) — so conv-net remat
      only translates into a *measured* drop on the TPU backend; the
      attention models (whose win is not storing the [B,H,T,T] score
      tensors) drop on both."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import flags, models
    from paddle_tpu.analysis import memory as memplan

    on_tpu = jax.default_backend() != "cpu"
    if on_tpu:
        kw = dict(d_model=768, n_layers=12, n_heads=12, d_inner=3072)
        vocab, batch_cap = 30522, 1024
    else:
        kw = dict(d_model=128, n_layers=2, n_heads=2, d_inner=256)
        vocab, batch_cap = 1000, 64
    frac = float(flags.get_flag("hbm_budget_frac")) or 0.9

    def bert_build(batch):
        main, startup, h = models.bert.get_model(
            batch_size=batch, seq_len=seq_len, vocab_size=vocab,
            dropout=0.1, lr=1e-4, max_position=max(512, seq_len), **kw)
        feed = models.bert.make_fake_batch(batch, seq_len, vocab,
                                           kw["n_heads"])
        return main, startup, h["loss"], feed

    def resnet_build(batch):
        main, startup, h = models.resnet.get_model(
            dataset="imagenet", depth=50, class_num=1000, lr=0.1)
        rng = np.random.RandomState(0)
        feed = {"img": rng.randn(batch, 3, 224, 224).astype(np.float32),
                "label": rng.randint(0, 1000, (batch, 1)).astype(np.int64)}
        return main, startup, h["loss"], feed

    def compile_peak(build, batch, opt_level):
        """(xla_peak_bytes, plan_predicted_peak_bytes) — the latter None
        below opt 3 (no plan is computed)."""
        main, startup, loss, feed = build(batch)
        exe = fluid.Executor()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            res = exe.cost_analysis(main, feed=feed, fetch_list=[loss],
                                    opt_level=opt_level)
        predicted = max((c.memory_plan.predicted_peak_bytes
                         for c in exe.engine._cache.values()
                         if c.memory_plan is not None), default=None)
        mem = res["memory"]
        if mem is None:
            return None, predicted
        arg = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
        outb = int(getattr(mem, "output_size_in_bytes", 0) or 0)
        tmp = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
        alias = int(getattr(mem, "alias_size_in_bytes", 0) or 0)
        return arg + max(0, outb - alias) + tmp, predicted

    out = {}
    budget = memplan.hbm_budget_bytes()
    if budget is None:
        budget = int(16 * (1 << 30) * frac)
    out["memory_hbm_budget_bytes"] = int(budget)

    def fits(b):
        p, _ = compile_peak(bert_build, b, 3)
        return p is not None and p <= budget

    lo, b = 0, 1
    while b <= batch_cap and fits(b):
        lo, b = b, b * 2
    if lo and b <= batch_cap:
        hi = b  # first known-failing batch; bisect the gap
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if fits(mid):
                lo = mid
            else:
                hi = mid
    out["bert_seq%d_max_batch" % seq_len] = lo

    for name, build, batch in (
            ("bert_seq%d" % seq_len, bert_build, 4 if on_tpu else 2),
            ("resnet50", resnet_build, 512 if on_tpu else 4)):
        p2, _ = compile_peak(build, batch, 2)
        if not p2:
            continue
        out[name + "_peak_hbm_bytes_opt2"] = int(p2)
        main, _, loss, feed = build(batch)
        plan = memplan.plan_memory(
            main.desc, feed_shapes={k: v.shape for k, v in feed.items()},
            fetch_names=[loss.name])
        tight = int(0.6 * min(p2, plan.liveness.peak_bytes) / frac)
        flags.set_flags({"device_memory_bytes": max(tight, 1)})
        try:
            p3, predicted = compile_peak(build, batch, 3)
        finally:
            flags.reset_flag("device_memory_bytes")
        if p3:
            out[name + "_peak_hbm_bytes_opt3"] = int(p3)
        if predicted:
            out[name + "_plan_predicted_peak_bytes"] = int(predicted)
    return out


def bench_serving():
    """PADDLE_TPU_BENCH=serving block: the inference pipeline end to end
    — freeze, INT8 post-training quantization, continuous-batching
    server — on whatever backend JAX selects.

    Emits ``resnet50_int8_images_per_sec`` (cifar depth-20 resnet, the
    CPU-probe stand-in multichip_probe.py also uses) against the fp32
    frozen rate, plus ``bert_base_served_qps`` / ``bert_base_served_p99_ms``
    from the server's own SLO histograms under a Poisson load at ~0.8x
    measured capacity. Honesty note on ``int8_speedup_vs_fp32``: on the
    CPU backend the int8 path runs the exact fp32 emulation
    (ops/quant_ops.py — XLA CPU's native s8xs8->s32 dot is 5-50x SLOWER
    than f32, measured), so the ratio sits near 1.0 there; the 3x+
    headline lives on hardware with an int8 MXU path where
    ``int8_native`` resolves to the s32-accumulate kernels."""
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import (
        InferenceServer,
        freeze_program,
        post_training_quantize,
    )

    on_tpu = jax.default_backend() != "cpu"
    rng = np.random.RandomState(0)
    out = {}

    # -- resnet: fp32 frozen vs int8 request rate -------------------------
    main_p, startup, h = models.resnet.get_model(
        dataset="cifar10", depth=20, class_num=10, lr=0.1)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    feed_names, fetch_names = ["img"], [h["logits"].name]
    frozen, _ = freeze_program(main_p, feed_names, fetch_names, scope=scope)
    batch = 256 if on_tpu else 32

    def mk(n):
        return {"img": rng.randn(n, 3, 32, 32).astype(np.float32)}

    int8_prog, _, qrep = post_training_quantize(
        frozen, [mk(batch) for _ in range(4)], feed_names, fetch_names,
        scope=scope, executor=exe, max_batches=4)
    out["serving_quantized_ops"] = len(qrep.quantized)

    def rate(prog, steps=15, warmup=3):
        feed = mk(batch)
        with fluid.scope_guard(scope):
            run = lambda: exe.run(prog, feed=feed, fetch_list=fetch_names,
                                  return_numpy=False)[0]
            ips, _ = _throughput(run, batch, steps, warmup)
        return ips

    fp32_ips = rate(frozen)
    int8_ips = rate(int8_prog)
    out["resnet50_fp32_frozen_images_per_sec"] = round(fp32_ips, 2)
    out["resnet50_int8_images_per_sec"] = round(int8_ips, 2)
    out["int8_speedup_vs_fp32"] = round(int8_ips / fp32_ips, 3)

    # -- bert: served QPS + p99 under Poisson load ------------------------
    if on_tpu:
        kw = dict(d_model=768, n_layers=12, n_heads=12, d_inner=3072)
        seq_len, vocab = 128, 30522
    else:
        kw = dict(d_model=128, n_layers=2, n_heads=2, d_inner=256)
        seq_len, vocab = 32, 512
    bmain, bstartup, bh = models.bert.get_model(
        batch_size=4, seq_len=seq_len, vocab_size=vocab, dropout=0.0,
        lr=1e-4, max_position=512, **kw)
    bexe = fluid.Executor()
    bscope = fluid.Scope()
    with fluid.scope_guard(bscope):
        bexe.run(bstartup)
    enc_feeds = ["src_ids", "pos_ids", "sent_ids", "seq_lens"]
    bfetch = [bh["enc_out"].name]
    bfrozen, _ = freeze_program(bmain, enc_feeds, bfetch, scope=bscope)

    def bert_feed(n):
        b = models.bert.make_fake_batch(n, seq_len, vocab, kw["n_heads"],
                                        rng=rng)
        return {k: b[k] for k in enc_feeds}

    bint8, _, _ = post_training_quantize(
        bfrozen, [bert_feed(4) for _ in range(4)], enc_feeds, bfetch,
        scope=bscope, executor=bexe, max_batches=4)

    buckets = (1, 2, 4, 8)
    server = InferenceServer(bint8, enc_feeds, bfetch, scope=bscope,
                             executor=bexe, buckets=buckets,
                             max_wait_ms=5.0, name="bench")
    with server:
        server.warmup(bert_feed(1))
        # capacity from the top bucket: rows/sec of the padded executable
        t0 = time.perf_counter()
        cap_runs = 6
        for _ in range(cap_runs):
            server.run(bert_feed(buckets[-1]))
        capacity_qps = cap_runs * buckets[-1] / (time.perf_counter() - t0)
        target_qps = max(1.0, 0.8 * capacity_qps)
        duration = 4.0
        futures = []
        t0 = time.perf_counter()
        next_t = t0
        while True:
            next_t += rng.exponential(1.0 / target_qps)
            if next_t >= t0 + duration:
                break
            delay = next_t - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            futures.append(server.submit(bert_feed(1)))
        for f in futures:
            f.result(timeout=600)
        elapsed = time.perf_counter() - t0
    req_h = obs.snapshot()["histograms"].get("serving.request_ms") or {}
    out["bert_base_served_qps"] = round(len(futures) / elapsed, 2)
    if req_h.get("p99") is not None:
        out["bert_base_served_p99_ms"] = round(req_h["p99"], 2)
    return out


def bench_layout(batch=None, steps=30, warmup=5):
    """PADDLE_TPU_BENCH=layout block: ResNet-50 train throughput with the
    whole-program NHWC layout pass (analysis/layout.py, opt level 4) vs
    the same build in framework-native NCHW — both at the same opt level
    so the ONLY delta is the layout assignment. Also publishes the pass's
    own minimality evidence: ``layout_transpose_count`` (inserted seam
    transposes — 3 on this model: feed in, flatten-out, flatten-grad
    back) and ``layout_nhwc_ops`` from a dry-run plan of the same
    program, so a future change that starts spraying transposes fails
    tools/bench_diff.py even if throughput noise masks it."""
    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import flags, models
    from paddle_tpu.analysis import plan_layout

    on_tpu = jax.default_backend() != "cpu"
    batch = batch or (512 if on_tpu else 4)
    if not on_tpu:
        steps, warmup = min(steps, 10), min(warmup, 2)

    def _run(layout_mode):
        main, startup, h = models.resnet.get_model(
            dataset="imagenet", depth=50, class_num=1000, lr=0.1)
        if os.environ.get("PADDLE_TPU_AMP", "1") != "0":
            fluid.contrib.mixed_precision.enable_bf16(main)
        exe = fluid.Executor()
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        x = jax.device_put(rng.randn(batch, 3, 224, 224).astype(np.float32))
        y = jax.device_put(
            rng.randint(0, 1000, (batch, 1)).astype(np.int64))
        old = {"opt_level": flags.get_flag("opt_level"),
               "layout": flags.get_flag("layout")}
        # both sides run the FULL level-4 pipeline; only the layout
        # flag differs, so the ratio isolates the NHWC rewrite
        flags.set_flags({"opt_level": 4, "layout": layout_mode})
        try:
            with fluid.scope_guard(scope):
                exe.run(startup)
                step = lambda: exe.run(main, feed={"img": x, "label": y},
                                       fetch_list=[h["loss"]],
                                       return_numpy=False)[0]
                ips, loss = _throughput(step, batch, steps, warmup)
        finally:
            flags.set_flags(old)
        assert np.isfinite(loss)
        return ips, main, h

    ips_nchw, _, _ = _run("off")
    ips_nhwc, main, h = _run("nhwc")
    plan = plan_layout(main.desc, feed_names=["img", "label"],
                       fetch_names=[h["loss"].name])
    return {
        "resnet50_nchw_images_per_sec": round(ips_nchw, 2),
        "resnet50_nhwc_images_per_sec": round(ips_nhwc, 2),
        "layout_nhwc_speedup": round(ips_nhwc / ips_nchw, 3)
        if ips_nchw else 0.0,
        "layout_transpose_count": plan.transpose_count,
        "layout_nhwc_ops": plan.n_nhwc_ops,
        "layout_weights_baked": len(plan.weights),
    }


def bench_pipeline(steps=60, warmup=8, depth=8, reps=5):
    """PADDLE_TPU_BENCH=pipeline block: the async-dispatch window, the
    double-buffered input prefetch, and the off-critical-path checkpoint
    snapshot, each measured at its own seam (engine/pipeline.py,
    checkpoint.py).

    Methodology (honest on the CPU probe): every headline here is a
    RATIO of two walls measured the same way in the same process — the
    backend's absolute speed cancels, so the numbers say whether the
    pipelining removes host-side serialization, not how fast the chip
    is. On a tunneled TPU the same code paths hide ~100 ms host round
    trips instead of ~µs device_get calls, so the fractions only grow.

    * ``pipeline_depth{1,N}_steps_per_sec`` — the same MLP train step
      driven with a per-step host read (depth 1: ``run()`` returns
      numpy, one device_get per step — the synchronous engine's loop)
      vs through the dispatch window (``dispatch_steps=N``: ``run()``
      returns DeferredFetch placeholders, ONE drain at the end). The
      2-layer MLP step is dispatch-overhead-scale on purpose: that is
      the regime where the per-step host sync is the cost, i.e. exactly
      what the window removes. Median of ``reps`` windows.
    * ``pipeline_input_overhead_frac_{sync,prefetch}`` — wall of a loop
      fed fresh HOST batches inline vs through PrefetchingFeeder, each
      normalized against the pre-staged (device-resident feed) wall:
      ``frac = 1 - staged_wall/measured_wall``, clamped at 0. Each host
      batch owes a reader-chain normalize/augment pass before the
      transfer; the prefetch fraction dropping is that work + the H2D
      leaving the critical path.
    * ``ckpt_critical_path_ms_{blocking,async}`` and
      ``ckpt_wall_hidden_frac`` — per-call wall of
      ``CheckpointManager.save()`` with blocking=True vs blocking=False
      (the async call pays only the device→host snapshot kickoff;
      serialization + fsync ride the writer thread). hidden = 1 -
      async/blocking. ``wait()`` drains before the directory is
      removed, so the async saves are real published checkpoints, not
      dropped work.
    """
    import shutil
    import tempfile

    import jax
    import paddle_tpu.fluid as fluid
    from paddle_tpu import models
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.engine.pipeline import PrefetchingFeeder

    batch = 512
    rng = np.random.RandomState(0)
    # rotating pool of distinct host buffers: the fed loops move a fresh
    # batch every step without holding `steps` batches in RAM
    pool = [(rng.randn(batch, 784).astype(np.float32),
             rng.randint(0, 10, (batch, 1)).astype(np.int64))
            for _ in range(3)]
    main, startup, h = models.mnist.get_model(lr=0.01)
    exe = fluid.Executor()
    scope = fluid.Scope()
    out = {}
    with fluid.scope_guard(scope):
        exe.run(startup)
        dev_feed = {"img": jax.device_put(pool[0][0]),
                    "label": jax.device_put(pool[0][1])}

        # -- multi-step dispatch: depth 1 vs depth N ----------------------
        def window_wall(d):
            for _ in range(warmup):
                exe.run(main, feed=dev_feed, fetch_list=[h["loss"]],
                        dispatch_steps=d)
            exe.sync()
            t0 = time.perf_counter()
            last = None
            for _ in range(steps):
                last = exe.run(main, feed=dev_feed,
                               fetch_list=[h["loss"]],
                               dispatch_steps=d)[0]
            exe.sync()  # drain the window inside the timed region
            elapsed = time.perf_counter() - t0
            assert np.isfinite(float(np.asarray(last).reshape(-1)[0]))
            return elapsed

        d1 = float(np.median([window_wall(1) for _ in range(reps)]))
        dn = float(np.median([window_wall(depth) for _ in range(reps)]))
        out["pipeline_depth1_steps_per_sec"] = round(steps / d1, 2)
        out["pipeline_depth%d_steps_per_sec" % depth] = round(
            steps / dn, 2)
        out["pipeline_dispatch_speedup"] = round(d1 / dn, 3)
        out["pipeline_dispatch_depth"] = depth

        # -- input prefetch: inline vs PrefetchingFeeder ------------------
        # the reader owes each batch a normalize/augment pass (the
        # decode+augment work every real input chain does; GIL-releasing
        # numpy ufunc loops, ~2 ms at this size) — the host-side input
        # work the feeder's thread moves off the critical path. On the
        # CPU probe the H2D transfer itself is ~free, so this reader
        # work IS the overlappable signal.
        pool_wire = [(x.astype(np.float64), y) for x, y in pool]

        def host_batches(n):
            for i in range(n):
                x, y = pool_wire[i % len(pool_wire)]
                img = np.sqrt(np.abs(x) * 0.5 + 0.25).astype(np.float32)
                yield {"img": img, "label": y}

        # per-step host read (return_numpy=True) on purpose: under async
        # dispatch an inline convert already overlaps the PREVIOUS step's
        # compute, so a read-free loop shows no input overhead to remove.
        # The loop every fluid training script actually writes reads its
        # loss each step — there the convert serializes (read blocks ->
        # convert -> dispatch), and the feeder's background thread is
        # what restores the overlap.
        def fed_wall(feed_iter):
            t0 = None
            for i, fd in enumerate(feed_iter):
                if i == warmup:
                    t0 = time.perf_counter()
                val = exe.run(main, feed=fd, fetch_list=[h["loss"]])[0]
            assert np.isfinite(float(np.asarray(val).reshape(-1)[0]))
            return time.perf_counter() - t0

        total = warmup + steps
        staged = float(np.median(
            [fed_wall(dev_feed for _ in range(total))
             for _ in range(reps)]))

        def prefetched():
            with PrefetchingFeeder(lambda: host_batches(total)) as f:
                return fed_wall(f)

        inline = float(np.median(
            [fed_wall(host_batches(total)) for _ in range(reps)]))
        pre = float(np.median([prefetched() for _ in range(reps)]))
        out["pipeline_input_overhead_frac_sync"] = round(
            max(0.0, 1.0 - staged / inline), 4)
        out["pipeline_input_overhead_frac_prefetch"] = round(
            max(0.0, 1.0 - staged / pre), 4)

    # -- checkpoint: blocking vs async critical path ----------------------
    # device-resident state sized so serialization is measurable (~8 MB)
    arrays = {"w%d" % i: jax.device_put(
        rng.randn(256, 1024).astype(np.float32)) for i in range(8)}
    root = tempfile.mkdtemp(prefix="pipe_bench_ckpt_")
    try:
        mgr = CheckpointManager(root, max_to_keep=2)
        n_saves = 6
        mgr.save(0, arrays, blocking=True)  # warm the path
        t0 = time.perf_counter()
        for i in range(n_saves):
            mgr.save(10 + i, arrays, blocking=True)
        t_block = (time.perf_counter() - t0) / n_saves
        t0 = time.perf_counter()
        for i in range(n_saves):
            mgr.save(100 + i, arrays, blocking=False)
        t_async = (time.perf_counter() - t0) / n_saves
        mgr.wait()   # the saves above must really publish
        mgr.check_error()
        out["ckpt_critical_path_ms_blocking"] = round(t_block * 1e3, 3)
        out["ckpt_critical_path_ms_async"] = round(t_async * 1e3, 3)
        out["ckpt_wall_hidden_frac"] = round(
            max(0.0, 1.0 - t_async / t_block), 4)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_health_overhead():
    """Cost of the liveness layer at each of its three seams — proving
    the health PR stays off the step path:

    * ``note_step_ns`` — the ONE call the engine makes per step (an int
      bump + a clock read); must stay in the ns regime.
    * ``heartbeat_emit_us`` — one full heartbeat build+emit (RSS read,
      phase, tracer event, sink flush attempt); runs on a daemon thread
      once per second, so µs here is noise.
    * ``classify_8rank_us`` — one supervisor classification round over
      8 synthetic ranks; runs in wait_gang's poll loop.
    """
    import time

    from paddle_tpu.observability import health

    out = {}
    health.reset_steps()
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        health.note_step()
    out["note_step_ns"] = round((time.perf_counter() - t0) / n * 1e9, 1)

    em = health.HeartbeatEmitter(interval_ms=60000.0)
    beats = 200
    t0 = time.perf_counter()
    for _ in range(beats):
        em.emit_now()
    out["heartbeat_emit_us"] = round(
        (time.perf_counter() - t0) / beats * 1e6, 2)
    out["heartbeats_emitted"] = beats

    ranks = {}
    base = 1700000000.0
    for r in range(8):
        rh = ranks[r] = health.RankHealth(r, heartbeat_ms=1000.0)
        for i in range(32):
            rh.observe({"name": health.HEARTBEAT_EVENT,
                        "ts": (base + i) * 1e6,
                        "args": {"seq": i + 1, "step": i * 3}})
    rounds = 1000
    now = base + 33.0
    t0 = time.perf_counter()
    for _ in range(rounds):
        for rh in ranks.values():
            rh.status(now, 0.0, base)
    out["classify_8rank_us"] = round(
        (time.perf_counter() - t0) / rounds * 1e6, 2)
    health.reset_steps()
    return out


def bench_elastic():
    """Cost of acting on a health verdict — the three elastic paths:

    * ``local_restore_ms`` vs ``quorum_restore_ms`` — the same ~8 MB
      checkpoint read back from the local root, then (local root wiped)
      from a peer replica; the delta is the full price of surviving
      ``disk_fail``, and it should be a file-copy read, not a rebuild.
    * ``router_reaction_ms`` — wall time from a worker's fast window
      starting to burn to the FleetRouter's poll thread landing the
      scale-out; dominated by the poll interval, so ms here proves the
      detection loop is not the autoscale bottleneck (worker spawn is).
    * ``shrink_rejit_ms`` — one engine step after a device is marked
      lost under ``mesh=dp=-1``: mesh re-plan + fresh compile + donated
      state reshard, i.e. the training gap a shrink inserts. ``None``
      on single-device hosts (nothing to shrink onto).
    """
    import shutil
    import tempfile
    import time

    import jax

    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.resilience.elastic import FleetRouter

    out = {}
    tmp = tempfile.mkdtemp(prefix="bench_elastic_")
    try:
        local = os.path.join(tmp, "local")
        peers = [os.path.join(tmp, "p1"), os.path.join(tmp, "p2")]
        state = {"w%d" % i: np.random.RandomState(i).randn(
            256, 1024).astype(np.float32) for i in range(8)}
        mgr = CheckpointManager(local, replica_roots=peers, replicas=2)
        mgr.save(10, state, blocking=True)
        t0 = time.perf_counter()
        mgr.restore(10)
        out["local_restore_ms"] = round(
            (time.perf_counter() - t0) * 1000.0, 2)
        shutil.rmtree(local)
        os.makedirs(local)
        mgr = CheckpointManager(local, replica_roots=peers, replicas=2)
        t0 = time.perf_counter()
        mgr.restore(10)
        out["quorum_restore_ms"] = round(
            (time.perf_counter() - t0) * 1000.0, 2)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    class _W:  # duck-typed worker: isolates the router's own latency
        def __init__(self, idx):
            self.burn = False

        def alive(self):
            return True

        def burning(self, now=None):
            return self.burn

        fast_burning = burning

        def slow_recovered(self, now=None):
            return True

        def burn_snapshot(self, now=None):
            return {"burn_fast": 5.0 if self.burn else 0.0,
                    "burn_slow": 0.0, "fast_threshold": 2.0,
                    "slow_threshold": 3.0}

        def start(self):
            pass

        def stop(self):
            pass

    router = FleetRouter(_W, min_workers=1, max_workers=2, cooldown_s=0.0)
    router.start(poll_interval_s=0.01)
    try:
        t0 = time.perf_counter()
        router.workers[0].burn = True
        deadline = t0 + 5.0
        while router.scale_outs < 1 and time.perf_counter() < deadline:
            time.sleep(0.001)
        out["router_reaction_ms"] = round(
            (time.perf_counter() - t0) * 1000.0, 2) \
            if router.scale_outs else None
    finally:
        router.stop()

    out["shrink_rejit_ms"] = None
    if len(jax.devices()) >= 2:
        import paddle_tpu.fluid as fluid
        from paddle_tpu import flags as _flags
        from paddle_tpu.framework import Program, program_guard
        from paddle_tpu.resilience import elastic

        main, startup = Program(), Program()
        with program_guard(main, startup):
            img = fluid.layers.data(name="ex", shape=[64],
                                    dtype="float32")
            hid = fluid.layers.fc(input=img, size=64, act="relu")
            loss = fluid.layers.mean(fluid.layers.fc(input=hid, size=8))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor()
        scope = fluid.Scope()
        feed = {"ex": np.random.RandomState(0).randn(
            16, 64).astype(np.float32)}
        _flags.set_flags({"mesh": "dp=-1"})
        try:
            with fluid.scope_guard(scope):
                exe.run(startup)
                exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
                elastic.mark_device_lost(jax.devices()[-1])
                t0 = time.perf_counter()
                exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
                out["shrink_rejit_ms"] = round(
                    (time.perf_counter() - t0) * 1000.0, 2)
        finally:
            elastic.reset_lost()
            _flags.reset_flag("mesh")
    return out


def bench_sentinel():
    """Cost of the SDC sentinel (resilience/sentinel.py), both ways:

    * ``digest_overhead_frac`` — per-step wall tax of PADDLE_TPU_SDC=1
      on a compute-heavy CPU probe (512-wide MLP, batch 2048): the
      fused in-graph digest plus the host-side seam recompute and
      retention. Sentinel cost is O(params) while step compute is
      O(batch x params), so the probe uses a training-realistic batch —
      a toy batch would measure the digest against almost no compute
      and overstate the tax by an order of magnitude. The acceptance
      bar is < 0.05; a regression here means the digest stopped fusing
      or the retention started copying.
    * ``detect_to_blame_ms`` — wall from the suspect raise at retire to
      the replay vote convicting the device (deterministic re-execution
      + recompute + verdict), i.e. the training gap one corruption
      inserts before quarantine can even start.
    """
    import time

    import paddle_tpu.fluid as fluid
    from paddle_tpu import flags as _flags
    from paddle_tpu.framework import Program, program_guard
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.resilience.sentinel import SDCSuspect

    def build():
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = fluid.layers.data(name="sx", shape=[512], dtype="float32")
            h = fluid.layers.fc(input=x, size=512, act="relu")
            h = fluid.layers.fc(input=h, size=512, act="relu")
            loss = fluid.layers.mean(fluid.layers.fc(input=h, size=10))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        return main, startup, loss

    feed = {"sx": np.random.RandomState(3).randn(
        2048, 512).astype(np.float32)}
    warm, meas = 3, 16

    def make(sdc):
        _flags.set_flags({"sdc": sdc})
        main, startup, loss = build()
        exe = fluid.Executor()
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        for _ in range(warm):
            exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        return exe, main, loss, scope

    # PAIRED measurement: the off and on steps alternate inside the
    # same time window, so machine drift (turbo states, noisy
    # neighbors) hits both sides equally instead of masquerading as
    # sentinel overhead; medians then drop scheduler hiccups
    try:
        off = make(False)
        on = make(True)
        off_w, on_w = [], []
        for _ in range(meas):
            for sdc, run, walls in ((False, off, off_w), (True, on, on_w)):
                _flags.set_flags({"sdc": sdc})
                exe, main, loss, scope = run
                t0 = time.perf_counter()
                exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
                walls.append(time.perf_counter() - t0)
        off_w.sort()
        on_w.sort()
        # lower quartile, not median: the question is the sentinel's
        # structural cost, so quantify the clean-machine steps — load
        # spikes land on both sides but not always symmetrically
        base = off_w[len(off_w) // 4]
        armed = on_w[len(on_w) // 4]
    finally:
        _flags.reset_flag("sdc")
    out = {
        "step_ms_off": round(base * 1000.0, 3),
        "step_ms_on": round(armed * 1000.0, 3),
        "digest_overhead_frac": round(max(0.0, armed - base)
                                      / max(base, 1e-9), 4),
    }

    # detect -> blame: a PERSISTENT flip (x5: every replay corrupts
    # again) convicted by the replay vote, timed from the suspect raise
    out["detect_to_blame_ms"] = None
    _flags.set_flags({"sdc": True, "fault_spec": "bitflip@step5:x5"})
    faultinject.reset()
    try:
        main, startup, loss = build()
        exe = fluid.Executor()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(8):
                try:
                    exe.run(main, feed=feed, fetch_list=[loss],
                            scope=scope)
                except SDCSuspect as e:
                    t0 = time.perf_counter()
                    verdict = exe.engine.sdc_recover(
                        e.step, reason=e.reason)
                    if verdict["kind"] == "blamed":
                        out["detect_to_blame_ms"] = round(
                            (time.perf_counter() - t0) * 1000.0, 2)
                    break
    finally:
        _flags.reset_flag("sdc")
        _flags.reset_flag("fault_spec")
        faultinject.reset()
    return out


def bench_goodput():
    """Steady-state goodput fraction + MFU attribution for a 50-step
    CPU probe (observability/goodput.py ledger).

    Warmup covers the jit compile, then the ledger resets so the
    measured window is pure steady state — the same protocol a real
    deployment uses when it reports goodput over a training day rather
    than over the first compile. The acceptance bar for the clean probe
    is ``goodput_frac >= 0.99`` with ``conservation_err < 0.01``:
    anything lower means host work between the engine seams is being
    misfiled as badput, i.e. the ledger itself regressed, since this
    probe injects no faults. ``mfu`` stays None on CPU unless
    PADDLE_TPU_PEAK_FLOPS is exported; the raw achieved FLOP/s still
    rides along so rounds can trend it.
    """
    import paddle_tpu.fluid as fluid
    from paddle_tpu import flags as _flags
    from paddle_tpu.framework import Program, program_guard
    from paddle_tpu.observability import goodput as _goodput

    _flags.set_flags({"goodput": True})
    try:
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = fluid.layers.data(name="gx", shape=[256], dtype="float32")
            h = fluid.layers.fc(input=x, size=256, act="relu")
            loss = fluid.layers.mean(fluid.layers.fc(input=h, size=10))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        feed = {"gx": np.random.RandomState(7).randn(
            256, 256).astype(np.float32)}
        exe = fluid.Executor()
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        for _ in range(2):
            exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        _goodput.reset()
        steps = 50
        for _ in range(steps):
            exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        snap = _goodput.snapshot()
    finally:
        _flags.reset_flag("goodput")
        _goodput.reset()
    cats = snap["categories"]
    wall = snap["wall_ms"]
    out = {
        "steps": snap["steps"],
        "goodput_frac": round(snap["goodput_frac"], 4),
        "wall_ms": round(wall, 1),
        "categories_ms": {c: round(m, 3)
                          for c, m in sorted(cats.items()) if m},
        "conservation_err": round(
            abs(sum(cats.values()) - wall) / max(wall, 1e-9), 6),
        "model_flops_per_step": snap["mfu"]["model_flops_per_step"],
        "achieved_flops_per_s": snap["mfu"]["achieved_flops_per_s"],
        "mfu": snap["mfu"]["mfu"],
    }
    return out


def bench_opprof():
    """Op-attributed device time for a short profiled probe
    (observability/opprof.py): a tiny fc training model runs three
    steps under jax.profiler, stop_profiler joins the xplane device
    events back to framework-op provenance tags, and the resulting
    opprof.* gauges ride here — per-op device ms (lower-better in
    bench_diff), the unattributed remainder, and the attributed
    fraction. On the CPU probe the events come from host XLA threads
    ("cpu-coarse" source) so the absolute ms are trend-only; the
    attribution JOIN is what this canaries — a clean probe must stay
    >= 0.95 attributed.
    """
    import shutil
    import tempfile

    import paddle_tpu.fluid as fluid
    from paddle_tpu import flags as _flags
    from paddle_tpu import observability as _obs
    from paddle_tpu import profiler as _prof
    from paddle_tpu.framework import Program, program_guard
    from paddle_tpu.observability import opprof as _opprof

    trace_dir = tempfile.mkdtemp(prefix="bench_opprof_")
    _flags.set_flags({"trace_dir": trace_dir})
    _opprof.reset()
    try:
        main_p, startup = Program(), Program()
        with program_guard(main_p, startup):
            x = fluid.layers.data(name="px", shape=[128], dtype="float32")
            h = fluid.layers.fc(input=x, size=128, act="relu")
            loss = fluid.layers.mean(fluid.layers.fc(input=h, size=10))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        feed = {"px": np.random.RandomState(11).randn(
            64, 128).astype(np.float32)}
        exe = fluid.Executor()
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        # warmup outside the trace: the compile wall would otherwise
        # dwarf the 3 profiled steps and skew every per-op share
        exe.run(main_p, feed=feed, fetch_list=[loss], scope=scope)
        _prof.start_profiler()
        for _ in range(3):
            exe.run(main_p, feed=feed, fetch_list=[loss], scope=scope)
        _prof.stop_profiler(
            profile_path=os.path.join(trace_dir, "profile"))
        gauges = _obs.snapshot()["gauges"]
        out = {}
        for key in ("attributed_frac", "unattributed_ms", "comm_ms"):
            v = gauges.get("opprof." + key)
            if v is not None:
                out[key] = round(v, 4)
        hot = sorted(
            ((k[len("opprof."):], v) for k, v in gauges.items()
             if k.startswith("opprof.pt.") and k.endswith("_ms")),
            key=lambda kv: -kv[1])
        for tag, v in hot[:8]:
            out[tag] = round(v, 3)
    finally:
        _flags.reset_flag("trace_dir")
        shutil.rmtree(trace_dir, ignore_errors=True)
    return out


def bench_reqtrace():
    """Request-tracing cost triangle (observability/reqtrace.py):

    * per-request instrumentation overhead, on (begin + the 4 serving
      spans + tail verdict, dropped) vs off (the cached-bool
      maybe_begin) — the ns the tail sampler charges a request that is
      NOT kept, which is nearly all of them;
    * kept-trace fraction under a Poisson load on a tiny served MLP at
      2x its single-row rate with the slow threshold at ~4x p50 — what
      fraction of production traffic the tail sampler would persist;
    * exemplar-lookup round-trip ms: the sink written by that load,
      loaded cold by tools/trace_query.py to resolve a latency
      histogram's exemplar trace to its waterfall summary — the
      SLO-page -> trace lookup an on-call actually performs.
    """
    import shutil
    import tempfile

    import paddle_tpu.fluid as fluid
    from paddle_tpu import flags as _flags
    from paddle_tpu import models
    from paddle_tpu import observability as _obs
    from paddle_tpu.inference import InferenceServer, freeze_program
    from paddle_tpu.observability import reqtrace as _rt

    tools_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools")
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import trace_query

    out = {}
    n = 3000

    def per_request_ns(reps=5):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _i in range(n):
                ctx = _rt.maybe_begin(None)
                if ctx is not None:
                    _rt.add_span(ctx, "queue", 0.0, 1.0, rows=1)
                    _rt.add_span(ctx, "coalesce", 0.0, 1.0)
                    _rt.add_span(ctx, "dispatch", 0.0, 1.0)
                    _rt.add_root_span(ctx, "request", 0.0, 1.0)
                    _rt.tracer.finish(ctx, 0.0)
            best = min(best, (time.perf_counter() - t0) / n)
        return best * 1e9

    # off: both flags 0 -> one cached-bool check per request
    _flags.set_flags({"trace_sample": 0.0, "trace_slow_ms": 0.0})
    out["request_overhead_off_ns"] = round(per_request_ns(), 1)
    # on (tail-buffered, verdict drops): slow threshold armed but never
    # tripped, no head sampling -> the steady-state production cost
    _flags.set_flags({"trace_slow_ms": 1e6, "trace_buffer": 8192})
    out["request_overhead_on_ns"] = round(per_request_ns(), 1)
    out["request_overhead_delta_ns"] = round(
        out["request_overhead_on_ns"] - out["request_overhead_off_ns"], 1)

    # -- kept fraction under Poisson load + the exemplar round-trip -----
    main_p, startup, h = models.mnist.get_model(lr=0.01)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    frozen, _ = freeze_program(main_p, ["img"], [h["logits"].name],
                               scope=scope)
    rng = np.random.RandomState(0)

    def one_row():
        return {"img": rng.randn(1, 784).astype(np.float32)}

    sink_dir = tempfile.mkdtemp(prefix="bench_reqtrace_")
    sink = os.path.join(sink_dir, "serve.jsonl")
    try:
        srv = InferenceServer(frozen, ["img"], [h["logits"].name],
                              scope=scope, executor=exe, buckets=(1, 4),
                              max_wait_ms=2.0, name="reqtrace-bench")
        with srv:
            srv.warmup(one_row())
            lat = []
            for _ in range(20):
                t0 = time.perf_counter()
                srv.run(one_row())
                lat.append((time.perf_counter() - t0) * 1000.0)
            p50 = sorted(lat)[len(lat) // 2]
            # metrics on explicitly (main() sets it too): the exemplar
            # round-trip below reads the histogram exemplar slots out
            # of the sink's final snapshot
            _flags.set_flags({"metrics": True, "trace_sample": 0.05,
                              "trace_slow_ms": max(5.0, 3.0 * p50)})
            _obs.reset()
            _obs.attach_sink(sink)
            futs = []
            t_end = time.monotonic() + 2.0
            nxt = time.monotonic()
            # past the coalescing batcher's absorption point, so the
            # queue grows and a slow tail actually exists (the exemplar
            # below must resolve to a KEPT trace) — but not so far that
            # every request blows the threshold and the kept fraction
            # saturates at 1.0
            qps = 3000.0 / max(p50, 1e-3)
            while True:
                nxt += rng.exponential(1.0 / qps)
                if nxt >= t_end:
                    break
                d = nxt - time.monotonic()
                if d > 0:
                    time.sleep(d)
                futs.append(srv.submit(one_row()))
            for f in futs:
                f.result(timeout=600)
            stats = _rt.stats()
            _obs.detach_sink()
        out["poisson_requests"] = stats["completed"]
        out["kept_trace_frac"] = round(stats["kept_frac"], 4)
        # exemplar round-trip: sink -> metric exemplar -> trace summary
        t0 = time.perf_counter()
        traces, _spans, snap = trace_query.load(
            trace_query.expand_paths([sink], merge=True))
        tid, _v = trace_query.exemplar_lookup(snap, "serving.request_ms")
        found = tid is not None and tid in traces
        if found:
            trace_query.summarize(tid, traces[tid])
        out["exemplar_lookup_ms"] = round(
            (time.perf_counter() - t0) * 1000.0, 2)
        out["exemplar_resolved"] = bool(found)
    finally:
        for name in ("trace_sample", "trace_slow_ms", "trace_buffer"):
            _flags.reset_flag(name)
        shutil.rmtree(sink_dir, ignore_errors=True)
    return out


def bench_admission():
    """Overload-protection cost triangle (inference/admission.py):

    * submit-path overhead ns, protection off (the unguarded enqueue)
      vs armed-but-admitting (bounded queue + deadline + predictive
      gate checks that all pass) — what every request pays once the
      stack is on;
    * shed/reject/expire fractions under a 2s Poisson load at ~4x the
      batcher's capacity with shedding armed and a live burn monitor —
      how much traffic graceful degradation turns away to keep the
      admitted p99 bounded (``rejected`` trends lower-is-better in
      bench_diff: a regression here means the gate turns away traffic
      the server could have served);
    * hedge win rate on a two-worker fleet with one worker slowed 25x —
      the fraction of hedged requests the fast replica actually wins.
    """
    import paddle_tpu.fluid as fluid
    from paddle_tpu import flags as _flags
    from paddle_tpu import models
    from paddle_tpu.inference import (
        DeadlineExceeded,
        InferenceServer,
        Rejected,
        freeze_program,
    )
    from paddle_tpu.observability.health import SloMonitor
    from paddle_tpu.resilience.elastic import FleetRouter

    main_p, startup, h = models.mnist.get_model(lr=0.01)
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    frozen, _ = freeze_program(main_p, ["img"], [h["logits"].name],
                               scope=scope)
    rng = np.random.RandomState(0)
    feed = {"img": rng.randn(1, 784).astype(np.float32)}

    def mk_server(name, **kw):
        return InferenceServer(frozen, ["img"], [h["logits"].name],
                               scope=scope, executor=exe,
                               buckets=(1, 4), max_wait_ms=2.0,
                               name=name, **kw)

    out = {}

    def submit_ns(srv, reps=5, burst=64, **submit_kw):
        best = float("inf")
        for _ in range(reps):
            futs = []
            t0 = time.perf_counter()
            for _i in range(burst):
                futs.append(srv.submit(feed, **submit_kw))
            dt = (time.perf_counter() - t0) / burst
            for f in futs:
                f.result(timeout=600)
            best = min(best, dt)
        return best * 1e9

    # -- submit-path overhead: off vs armed-but-admitting ---------------
    try:
        srv = mk_server("adm-off")
        with srv:
            srv.warmup(feed)
            out["submit_off_ns"] = round(submit_ns(srv), 1)
        _flags.set_flags({"queue_limit": 100000, "serving_shed": True})
        srv = mk_server("adm-on")   # flags are read at ctor
        with srv:
            srv.warmup(feed)
            out["submit_armed_ns"] = round(
                submit_ns(srv, deadline_ms=60000.0), 1)
        out["submit_delta_ns"] = round(
            out["submit_armed_ns"] - out["submit_off_ns"], 1)
    finally:
        for name in ("queue_limit", "serving_shed"):
            _flags.reset_flag(name)

    # -- turned-away fractions at 4x capacity with shedding live --------
    try:
        _flags.set_flags({"queue_limit": 32, "serving_shed": True})
        mon = SloMonitor(10000.0, target=0.9, fast_window_s=1.0,
                         slow_window_s=30.0, fast_burn=1.5,
                         slow_burn=3.0, name="adm-bench")
        srv = mk_server("adm-load", slo_monitor=mon)
        with srv:
            srv.warmup(feed)
            lat = []
            for _ in range(20):
                t0 = time.perf_counter()
                srv.run(feed)
                lat.append((time.perf_counter() - t0) * 1000.0)
            p50 = sorted(lat)[len(lat) // 2]
            slo_ms = max(20.0, 5.0 * p50)
            mon.slo_ms = slo_ms
            qps = 4.0 * (4.0 / max(p50, 1e-3)) * 1000.0
            futs, rejected, shed = [], 0, 0
            t_end = time.monotonic() + 2.0
            nxt = time.monotonic()
            n = 0
            while True:
                nxt += rng.exponential(1.0 / qps)
                if nxt >= t_end:
                    break
                d = nxt - time.monotonic()
                if d > 0:
                    time.sleep(d)
                n += 1
                try:
                    futs.append(srv.submit(
                        feed, deadline_ms=0.6 * slo_ms))
                except Rejected as e:
                    if e.reason == "shed":
                        shed += 1
                    else:
                        rejected += 1
            served, expired = [], 0
            for f in futs:
                try:
                    f.result(timeout=600)
                    served.append((f.t_done - f.t_enq) * 1000.0)
                except DeadlineExceeded:
                    expired += 1
                except Rejected:
                    shed += 1
        out["overload_requests"] = n
        out["rejected_frac"] = round(rejected / max(1, n), 4)
        out["shed_frac"] = round(shed / max(1, n), 4)
        out["expired_frac"] = round(expired / max(1, n), 4)
        out["admitted_p99_ms"] = round(
            float(np.percentile(served, 99)), 2) if served else None
        out["admitted_slo_ms"] = round(slo_ms, 2)
    finally:
        for name in ("queue_limit", "serving_shed"):
            _flags.reset_flag(name)

    # -- hedge win rate against a 25x-slowed replica --------------------
    s0 = mk_server("adm-slow")
    s1 = mk_server("adm-fast")
    orig_run = s0._run_padded

    def slowed(feed_, bucket):
        time.sleep(0.05)
        return orig_run(feed_, bucket)

    s0._run_padded = slowed
    router = FleetRouter(lambda idx: (s0, s1)[idx], min_workers=2,
                         max_workers=2, cooldown_s=3600.0,
                         hedge_after_ms=10.0)
    router.start()
    try:
        s1.warmup(feed)
        for _ in range(40):
            router.submit(feed).result(timeout=600)
        out["hedges"] = router.hedges
        out["hedge_win_frac"] = round(
            router.hedge_wins / max(1, router.hedges), 4)
    finally:
        router.stop()
    return out


def main():
    from paddle_tpu import flags, observability

    # Telemetry rides along with every bench: the emitted JSON carries a
    # "counters" object (compile wall, cache hit/miss, transform fires)
    # so BENCH_*.json tracks the compile-time trajectory across rounds,
    # not just throughput. Near-zero in-loop cost (counter bumps at the
    # step seam, ~us against ms-scale steps).
    flags.set_flags({"metrics": True})
    which = os.environ.get("PADDLE_TPU_BENCH", "default")
    result = {
        "metric": "resnet50_train_images_per_sec",
        "value": 0.0,
        "unit": "images/sec",
        "vs_baseline": None,  # reference publishes no absolute throughput
    }
    errors = {}
    peak_hbm = {}

    def _try(name, fn):
        # Per-model HBM attribution: the memory watermarks reset before
        # each bench, so the peak after it is THIS model's footprint
        # (live-census + compile-time estimate; observability/memory.py).
        observability.memory.reset_peaks()
        try:
            v = round(float(fn()), 2)
        except Exception as e:  # noqa: BLE001
            errors[name] = str(e)[:200]
            return None
        peak = observability.memory.peak_hbm_bytes()
        if peak:
            peak_hbm[name] = int(peak)
        return v

    if which in ("default", "all", "resnet50"):
        v = _try("resnet50", bench_resnet50)
        if v:
            result["value"] = v
        v = _try("resnet50_pipelined", bench_resnet50_pipelined)
        if v:
            result["resnet50_pipelined_images_per_sec"] = v
        v = _try("resnet50_pipelined_u8",
                 lambda: bench_resnet50_pipelined(wire_dtype="uint8"))
        if v:
            result["resnet50_pipelined_u8_images_per_sec"] = v
    if which in ("default", "all", "bert"):
        v = _try("bert", bench_bert_base)
        if v:
            result["bert_base_samples_per_sec"] = v
        v = _try("bert_long", bench_bert_long)
        if v:
            result["bert_seq2048_samples_per_sec"] = v
        # seq-4096 b8 did not COMPILE before round 5's streamed flash
        # kernels (full-length residency overran scoped VMEM; MFU_r05.md)
        # — this key tracks that the long-context envelope stays open
        v = _try("bert_4k", lambda: bench_bert_long(
            batch=8, seq_len=4096, steps=8, warmup=2))
        if v:
            result["bert_seq4096_samples_per_sec"] = v
        v = _try("bert_pipelined", bench_bert_pipelined)
        if v:
            result["bert_pipelined_samples_per_sec"] = v
    if which in ("default", "all", "transformer"):
        v = _try("transformer", bench_transformer_nmt)
        if v:
            result["transformer_nmt_samples_per_sec"] = v
    if which in ("default", "all", "deepfm"):
        v = _try("deepfm", bench_deepfm)
        if v:
            result["deepfm_examples_per_sec"] = v
    if which in ("default", "all", "flash"):
        try:
            result.update(bench_flash_attention())
        except Exception as e:  # noqa: BLE001
            errors["flash"] = str(e)[:200]
    if which in ("all", "multichip"):
        # not in "default": the single-chip fallback forks 8 CPU
        # subprocesses — minutes of wall time the headline bench run
        # shouldn't absorb. PADDLE_TPU_BENCH=multichip is the MULTICHIP
        # bench-block selector.
        try:
            result.update(bench_multichip())
            if result["value"] == 0.0:  # multichip-only run: headline is
                dp = [k for k in result  # the widest resnet50 number
                      if k.startswith("resnet50_dp")
                      and k.endswith("images_per_sec")]
                if dp:
                    key = max(dp, key=lambda k: int(
                        k[len("resnet50_dp"):-len("_images_per_sec")]))
                    result["metric"] = key
                    result["value"] = result[key]
        except Exception as e:  # noqa: BLE001
            errors["multichip"] = str(e)[:200]
    pipeline_metrics = {}
    if which in ("all", "pipeline"):
        # not in "default": 3 x reps timed windows + 12 checkpoint
        # publishes is ~30s of wall clock; PADDLE_TPU_BENCH=pipeline is
        # the async-dispatch bench-block selector
        try:
            pipeline_metrics = bench_pipeline()
            result.update(pipeline_metrics)
            if result["value"] == 0.0:
                dk = [k for k in pipeline_metrics
                      if k.startswith("pipeline_depth")
                      and k.endswith("_steps_per_sec")
                      and k != "pipeline_depth1_steps_per_sec"]
                if dk:
                    result["metric"] = dk[0]
                    result["unit"] = "steps/sec"
                    result["value"] = pipeline_metrics[dk[0]]
        except Exception as e:  # noqa: BLE001
            errors["pipeline"] = str(e)[:200]
    serving_metrics = {}
    if which in ("all", "serving"):
        # not in "default": the Poisson load level runs ~10s of wall
        # clock; PADDLE_TPU_BENCH=serving is the INT8-serving selector
        try:
            serving_metrics = bench_serving()
            result.update(serving_metrics)
            if result["value"] == 0.0 and \
                    "resnet50_int8_images_per_sec" in serving_metrics:
                result["metric"] = "resnet50_int8_images_per_sec"
                result["unit"] = "images/sec"
                result["value"] = serving_metrics[
                    "resnet50_int8_images_per_sec"]
        except Exception as e:  # noqa: BLE001
            errors["serving"] = str(e)[:200]
    layout_metrics = {}
    if which in ("all", "layout"):
        # not in "default": two full ResNet-50 timed windows (NCHW +
        # NHWC) double the headline bench's wall clock;
        # PADDLE_TPU_BENCH=layout is the layout-pass A/B selector
        try:
            layout_metrics = bench_layout()
            result.update(layout_metrics)
            if result["value"] == 0.0 and \
                    "resnet50_nhwc_images_per_sec" in layout_metrics:
                result["metric"] = "resnet50_nhwc_images_per_sec"
                result["unit"] = "images/sec"
                result["value"] = layout_metrics[
                    "resnet50_nhwc_images_per_sec"]
        except Exception as e:  # noqa: BLE001
            errors["layout"] = str(e)[:200]
    if which in ("default", "all", "trace"):
        try:
            result.update(bench_trace_opt())
        except Exception as e:  # noqa: BLE001
            errors["trace"] = str(e)[:200]
    if which in ("default", "all", "memory"):
        try:
            result.update(bench_memory_planning())
        except Exception as e:  # noqa: BLE001
            errors["memory"] = str(e)[:200]
    if which in ("default", "all", "mnist") or result["value"] == 0.0:
        v = _try("mnist", bench_mnist_mlp)
        if v:
            # diagnostic only: a 2-layer-MLP step is pure dispatch
            # overhead on a tunneled chip and swings 2.5x across
            # sessions (MFU_r04.md) — never a headline number
            result["diag_mnist_mlp_examples_per_sec"] = v
            if result["value"] == 0.0:
                result["metric"] = "diag_mnist_mlp_train_examples_per_sec"
                result["unit"] = "examples/sec"
                result["value"] = v
    snap = observability.snapshot()
    c = snap["counters"]
    compile_h = snap["histograms"].get("engine.compile_ms", {})
    trace_h = snap["histograms"].get("engine.trace_ms", {})
    result["counters"] = {
        # first-call XLA compile + cache-miss build walls, summed over
        # every executable the run compiled
        "compile_wall_ms": round((compile_h.get("total") or 0.0)
                                 + (trace_h.get("total") or 0.0), 1),
        "executables_compiled": compile_h.get("count", 0),
        "cache_hits": c.get("engine.cache_hit", 0),
        "cache_misses": c.get("engine.cache_miss", 0),
        "cache_evictions": c.get("engine.cache_evict", 0),
        "transform_rewrites": {
            k[len("transform."):-len(".rewrites")]: v
            for k, v in sorted(c.items())
            if k.startswith("transform.") and k.endswith(".rewrites")
            and k != "transform.rewrites"},
        "transform_rewrites_total": c.get("transform.rewrites", 0),
        "nan_inf_trips": c.get("engine.nan_inf_trips", 0),
        # per-model device-memory high-watermark (bytes): BENCH_*.json
        # tracks memory alongside throughput across rounds
        "peak_hbm_bytes": peak_hbm,
        # resilience-layer activity (rollbacks, gang restarts, checkpoint
        # retries...): all zero on a healthy bench, so any non-zero value
        # in BENCH_*.json flags a run whose throughput number absorbed
        # recovery work
        "recovery": {k[len("recovery."):]: v
                     for k, v in sorted(c.items())
                     if k.startswith("recovery.")},
    }
    # async-dispatch / prefetch / async-ckpt activity: window depth and
    # retire accounting from the pipeline.* counters, merged with the
    # bench block's ratios when it ran, so BENCH_*.json trend tooling
    # that only diffs the counters object tracks the pipelining win
    result["counters"]["pipeline"] = dict(
        {k[len("pipeline."):]: v for k, v in sorted(c.items())
         if k.startswith("pipeline.")}, **pipeline_metrics)
    if serving_metrics:
        # the serving SLO numbers ride in counters too, so BENCH_*.json
        # trend tooling that only diffs the counters object sees them
        result["counters"]["serving"] = serving_metrics
    if layout_metrics:
        # layout A/B + seam-minimality evidence rides in counters too:
        # a transpose-count creep is a bench_diff failure even when
        # CPU-probe throughput noise hides the cost
        result["counters"]["layout"] = layout_metrics
    try:
        # liveness-layer on-path overhead (note_step/emit/classify):
        # tracked per round so a regression onto the step path is a
        # visible counters diff, not a silent throughput tax
        result["counters"]["health"] = bench_health_overhead()
    except Exception as e:  # noqa: BLE001
        errors["health"] = str(e)[:200]
    try:
        # elastic-path walls (quorum vs local restore, router reaction,
        # shrink re-jit): how long a health verdict takes to ACT on —
        # tracked per round, and in the serving selector too, so the
        # autoscale reaction budget shows up in BENCH_*.json trends
        result["counters"]["elastic"] = bench_elastic()
    except Exception as e:  # noqa: BLE001
        errors["elastic"] = str(e)[:200]
    try:
        # SDC sentinel: per-step digest tax (must stay < 5% on the CPU
        # probe) and the detect-to-blame replay wall — tracked per
        # round so arming the sentinel stays affordable by inspection
        result["counters"]["sentinel"] = bench_sentinel()
    except Exception as e:  # noqa: BLE001
        errors["sentinel"] = str(e)[:200]
    try:
        # wall-clock accounting: steady-state goodput fraction,
        # per-category ms, and the FLOPs-based MFU estimate for a
        # clean 50-step probe — the ledger's own regression canary
        # (a clean run must stay >= 0.99 goodput, conserving within 1%)
        result["counters"]["goodput"] = bench_goodput()
    except Exception as e:  # noqa: BLE001
        errors["goodput"] = str(e)[:200]
    try:
        # op-attributed device time: a 3-step profiled probe whose
        # xplane events join back to framework-op provenance tags —
        # per-op ms + attributed_frac trend across rounds, and a
        # dropped join (attribution regression) shows as the frac
        # collapsing, not as silent table rot
        result["counters"]["opprof"] = bench_opprof()
    except Exception as e:  # noqa: BLE001
        errors["opprof"] = str(e)[:200]
    try:
        # request-tracing cost triangle: per-request overhead on vs off
        # (the disabled path must stay a cached-bool check), the kept-
        # trace fraction under Poisson serving load, and the cold
        # exemplar->waterfall lookup through tools/trace_query.py
        result["counters"]["reqtrace"] = bench_reqtrace()
    except Exception as e:  # noqa: BLE001
        errors["reqtrace"] = str(e)[:200]
    try:
        # overload-protection cost triangle: the armed submit path's
        # per-request overhead vs off, turned-away fractions + admitted
        # p99 under 4x Poisson overload with shedding live, and the
        # hedge win rate against a deliberately slowed replica
        result["counters"]["admission"] = bench_admission()
    except Exception as e:  # noqa: BLE001
        errors["admission"] = str(e)[:200]
    if errors:
        result["errors"] = errors
    print(json.dumps(result))
    if result["value"] == 0.0:
        sys.exit(1)


if __name__ == "__main__":
    main()
