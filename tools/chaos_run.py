"""Chaos acceptance run: training under the supervised launcher with a
seeded random fault schedule, asserting the job still completes with the
fault-free result.

Two modes in one file so the supervisor respawns exactly this script:

* default (supervisor): builds a reproducible fault spec
  (``faultinject.random_spec``) — by default one worker kill plus one
  NaN trip at random steps — exports it as ``PADDLE_TPU_FAULT_SPEC``,
  runs ``--nproc`` workers under ``distributed.launch.supervise`` with a
  restart budget, then verifies every rank finished all steps AND
  (``--check-parity``) that each rank's loss trajectory matches a
  fault-free in-process run bit-for-bit. Prints a one-line JSON verdict;
  exits non-zero on any miss.
* ``--worker``: one training process — a small MLP + SGD driven by
  ``resilience.ResilientDriver`` with a per-rank checkpoint root under
  ``PADDLE_TPU_RECOVERY_CKPT``, writing its per-step losses to
  ``<result-dir>/rank<i>.json`` on completion. Restart-safe: a respawned
  worker resumes from its latest complete checkpoint.

Usage::

    python tools/chaos_run.py --steps 30 --nproc 2 --seed 7
    python tools/chaos_run.py --spec 'step_nan@9' --nproc 1
    python tools/chaos_run.py --hang --nproc 2        # heartbeat watchdog
    python tools/chaos_run.py --dispatch-steps 8 --nproc 1 \
        --spec 'step_nan@12'   # fault lands mid async dispatch window
    python tools/chaos_run.py --shrink --nproc 2      # permanent loss:
        # the highest rank exits LOST mid-run, the supervisor shrinks
        # the gang (health.mesh_shrunk) and the SURVIVORS finish all
        # steps with fault-free parity
    python tools/chaos_run.py --sdc --nproc 2         # silent corruption:
        # a transient bitflip on rank 0 is detected at that step's
        # retire, replayed clean, and absorbed; a PERSISTENT bitflip on
        # the highest rank is blamed by the replay vote, the rank exits
        # LOST, the supervisor shrinks, and the survivors finish with
        # bit-exact fault-free parity
    python tools/chaos_run.py --preempt --nproc 2     # graceful SIGTERM:
        # rank 0 drains + checkpoints + exits rc 46; the supervisor
        # restarts WITHOUT spending restart budget and the job completes
    python tools/chaos_run.py --shrink --mesh --zero1 --nproc 2
        # ZeRO-1 sharded update on the dp mesh: the Momentum velocity
        # slots live partitioned, the mid-run rank loss shrinks the
        # mesh (sharded state reshards onto the survivors), and the
        # trajectory must keep fault-free parity

CPU-only by construction (workers force JAX_PLATFORMS=cpu); the point
is recovery-path coverage, not throughput.
"""

import argparse
import glob
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CKPT_INTERVAL = 5


def _layout_mode():
    """--layout gate: both the supervisor's in-process reference run and
    the workers read the SAME env var, so the probe model (and the
    layout pass over it) is identical on both sides of the parity
    check."""
    return os.environ.get("PADDLE_TPU_LAYOUT", "").strip().lower() \
        == "nhwc"


def _zero1_mode():
    """--zero1 gate: reads the engine's own PADDLE_TPU_ZERO flag env so
    the probe model switches to Momentum (slot state for the sharded
    update to partition) identically in workers AND the supervisor's
    in-process parity reference — where the flag itself is inert
    because the reference runs mesh-less."""
    return os.environ.get("PADDLE_TPU_ZERO", "").strip().lower() \
        not in ("", "0", "false")


def build(lr=0.1):
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.framework import Program, program_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        if _layout_mode():
            # under --layout the probe grows a conv stem so the NHWC
            # pass has an anchor to rewrite (and a filter to bake HWIO
            # into the checkpointed scope — restart-after-bake is
            # exactly the reconciliation path worth chaosing)
            x = fluid.layers.data(name="x", shape=[1, 4, 4],
                                  dtype="float32")
            c = fluid.layers.conv2d(
                x, num_filters=4, filter_size=3, padding=1, act="relu",
                param_attr=fluid.ParamAttr(name="cw0"), bias_attr=False)
            h = fluid.layers.fc(input=c, size=16, act="relu",
                                param_attr=fluid.ParamAttr(name="cw1"),
                                bias_attr=False)
        else:
            x = fluid.layers.data(name="x", shape=[16], dtype="float32")
            h = fluid.layers.fc(input=x, size=16, act="relu",
                                param_attr=fluid.ParamAttr(name="cw1"),
                                bias_attr=False)
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        pred = fluid.layers.fc(input=h, size=4,
                               param_attr=fluid.ParamAttr(name="cw2"),
                               bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=pred, label=y))
        if _zero1_mode():
            fluid.optimizer.Momentum(learning_rate=lr,
                                     momentum=0.9).minimize(loss)
        else:
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    init = {
        "cw2": np.linspace(0.3, -0.3, 16 * 4).astype(
            np.float32).reshape(16, 4),
    }
    if _layout_mode():
        init["cw0"] = np.linspace(-0.2, 0.2, 4 * 1 * 3 * 3).astype(
            np.float32).reshape(4, 1, 3, 3)
        init["cw1"] = np.linspace(-0.4, 0.4, 64 * 16).astype(
            np.float32).reshape(64, 16)
    else:
        init["cw1"] = np.linspace(-0.4, 0.4, 16 * 16).astype(
            np.float32).reshape(16, 16)
    return main, startup, loss, init


def batch_fn(step, batch=16, seed=0):
    """Deterministic in ``step`` — the rewind/replay contract the
    ResilientDriver requires for exact post-recovery parity."""
    import numpy as np

    W = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    rng = np.random.RandomState(seed * 100003 + step)
    xv = rng.randn(batch, 16).astype(np.float32)
    yv = np.argmax(xv @ W, 1).astype(np.int64).reshape(-1, 1)
    if _layout_mode():
        xv = xv.reshape(batch, 1, 4, 4)
    return {"x": xv, "y": yv}


def train_losses(n_steps, ckpt_root, rank=0, max_rollbacks=8,
                 on_step=None, dispatch_steps=1, replica_roots=None):
    """Train the probe model under a ResilientDriver; returns the
    per-step scalar losses. Faults (if any are scheduled) fire through
    the engine's real seams; recovery is the driver's problem.
    ``dispatch_steps>1`` runs the loop through the engine's async
    dispatch window (engine/pipeline.py) — a fault then lands
    MID-WINDOW and the driver discards the in-flight steps before
    restoring."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import flags
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.resilience import ResilientDriver

    if dispatch_steps and dispatch_steps > 1:
        flags.set_flags({"dispatch_steps": int(dispatch_steps)})
    main, startup, loss, init = build()
    exe = fluid.Executor(fluid.TPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    for k, v in init.items():
        scope.set(k, v)
    mgr = CheckpointManager(ckpt_root, max_to_keep=4,
                            replica_roots=replica_roots)
    # context manager: close() joins the async checkpoint writer and
    # SURFACES any error it recorded — without it a failed background
    # save of the final state is silently lost at process exit
    with ResilientDriver(exe, main, [loss], mgr, scope=scope,
                         ckpt_interval=CKPT_INTERVAL,
                         max_rollbacks=max_rollbacks) as drv:
        results = drv.train(lambda s: batch_fn(s, seed=rank), n_steps,
                            on_step=on_step)
    return [float(np.asarray(r[0]).reshape(-1)[0]) for r in results]


def reassemble_steps(steps_path, n_steps):
    """Per-step JSONL (possibly spanning incarnations and rollback
    replays) -> full loss trajectory, last write per step winning.
    Returns None when any step is missing."""
    got = {}
    try:
        with open(steps_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail line from a kill mid-write
                got[rec["step"]] = rec["loss"]
    except OSError:
        return None
    if set(got) != set(range(n_steps)):
        return None
    return [got[s] for s in range(n_steps)]


def run_worker(args):
    # --mesh: 2 virtual devices so the dp-mesh GSPMD path (selected via
    # the inherited PADDLE_TPU_MESH flag) has something to shard over
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=%d"
        % (2 if args.mesh else 1))
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from paddle_tpu.resilience import SDCBlamed
    from paddle_tpu.resilience.faultinject import LOST_EXIT_CODE

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    nproc = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    root = os.environ.get("PADDLE_TPU_RECOVERY_CKPT") or os.path.join(
        args.result_dir, "ckpt")
    # elastic: a respawned worker inherits the supervisor's shrink count
    # and gives up on one virtual device per shrink — mesh_from_flag
    # then re-plans its dp=-1 axis over the survivors (the in-process
    # half of the capacity loss the gang shrink is the process half of)
    shrinks = int(os.environ.get("PADDLE_TPU_SHRINK_COUNT", "0"))
    if args.mesh and shrinks:
        from paddle_tpu.resilience import elastic

        for i in range(min(shrinks, 1)):     # 2 devices: 1 can go
            elastic.mark_device_lost(2 - 1 - i)
    # checkpoint quorum: with PADDLE_TPU_CKPT_REPLICAS > 0 each rank
    # mirrors its shards into its PEERS' roots, so a dead local disk
    # (disk_fail) restores from a surviving replica
    replica_roots = None
    if int(os.environ.get("PADDLE_TPU_CKPT_REPLICAS", "0") or 0) > 0:
        replica_roots = [os.path.join(root, "rank%d" % r)
                         for r in range(nproc) if r != rank]
    # stream every step's loss to an append-only per-rank JSONL: a
    # killed incarnation's in-memory results die with it, but this file
    # survives the respawn, so the full trajectory reassembles
    steps_path = os.path.join(args.result_dir, "rank%d.steps.jsonl" % rank)
    with open(steps_path, "a") as steps_f:
        # Resolution-aware streaming: forcing float(out[0]) on every
        # step would retire the dispatch window each time and serialize
        # it back to depth 1 — instead park placeholders and write them
        # once they resolve on their own (the window-overflow retire).
        # A killed incarnation loses at most the in-flight tail, which
        # the respawn replays from its checkpoint (last-write-wins in
        # reassemble_steps); rollback-discarded placeholders are
        # dropped, their replayed steps re-fire on_step.
        pending = []

        def _flush(force=False):
            while pending:
                s, v = pending[0]
                if getattr(v, "discarded", False):
                    pending.pop(0)
                    continue
                if not force and not getattr(v, "resolved", True):
                    break
                steps_f.write(json.dumps(
                    {"step": s,
                     "loss": float(np.asarray(v).reshape(-1)[0])}) + "\n")
                steps_f.flush()
                pending.pop(0)

        def on_step(step, out):
            pending.append((step, out[0]))
            _flush()

        try:
            train_losses(args.steps, os.path.join(root, "rank%d" % rank),
                         rank=rank, on_step=on_step,
                         dispatch_steps=args.dispatch_steps,
                         replica_roots=replica_roots)
        except SDCBlamed as e:
            # the sentinel's replay vote convicted OUR device of
            # persistent silent corruption and there is no in-process
            # spare to quarantine: flush what resolved (the discarded
            # in-flight tail drops itself), then exit LOST so the
            # supervisor shrinks the gang around this rank — the same
            # path a dead host takes, because that is what we now are
            _flush(force=True)
            from paddle_tpu import observability as obs

            # sentinel.blamed must be on disk for the verdict scan
            obs.flush_sink()
            print("chaos_run worker %d: %s; exiting LOST" % (rank, e),
                  file=sys.stderr)
            return LOST_EXIT_CODE
        _flush(force=True)   # train() drained the window; all resolved
    losses = reassemble_steps(steps_path, args.steps)
    if losses is None:
        print("chaos_run worker %d: incomplete step log" % rank,
              file=sys.stderr)
        return 1
    out = os.path.join(args.result_dir, "rank%d.json" % rank)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(losses, f)
    os.replace(tmp, out)
    return 0


def run_supervisor(args):
    from paddle_tpu import flags
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed.launch import supervise
    from paddle_tpu.resilience.faultinject import random_spec

    flags.set_flags({"metrics": True})
    kinds = (("worker_hang", "step_nan") if args.hang
             else ("worker_kill", "step_nan"))
    # --sdc injects at ENGINE step numbers (the bitflip seam lives in
    # the executor): startup run is engine step 1, batch 0 is engine
    # step 2, so batch step b corrupts at engine step b + 2
    sdc_transient = max(2, args.steps // 3) + 2
    sdc_persist = max(4, args.steps // 2) + 2
    if args.spec is not None:
        spec = args.spec
    elif args.shrink:
        # permanent loss of the HIGHEST rank (survivor ranks then keep
        # their ids — and their checkpoint roots — across the shrink)
        spec = "worker_loss@rank%d:step%d" % (
            args.nproc - 1, max(2, args.steps // 2))
    elif args.sdc:
        # one TRANSIENT flip on rank 0 (fires once; the replay is clean
        # and the step is absorbed) plus a PERSISTENT flip on the
        # highest rank (x9: every replay corrupts again, so the vote
        # blames the device and the rank exits LOST)
        spec = ("bitflip@step%d:rank0;bitflip@step%d:rank%d:x9"
                % (sdc_transient, sdc_persist, args.nproc - 1))
    elif args.preempt:
        # SIGTERM-style eviction of rank 0 mid-run: the driver drains,
        # checkpoints, and exits PREEMPT_EXIT_CODE; the supervisor
        # restarts the gang without spending restart budget
        spec = "preempt@step%d:rank0" % max(2, args.steps // 2)
    else:
        spec = random_spec(args.seed, args.steps, nproc=args.nproc,
                           kinds=kinds)
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_run_")
    result_dir = os.path.join(workdir, "results")
    ckpt_dir = os.path.join(workdir, "ckpt")
    os.makedirs(result_dir, exist_ok=True)
    sink = os.path.join(workdir, "metrics.jsonl")
    # the supervisor's own events (health.hang_detected, recovery.*)
    # land in the same sink family as the workers', host-tagged h99
    obs.attach_sink(sink, host=99)
    # kills AND watchdog-cleared hangs count against the restart budget;
    # everything else the workers absorb in-process
    max_restarts = args.max_restarts if args.max_restarts is not None \
        else max(2, spec.count("worker_kill")
                 + spec.count("worker_hang") + 1)
    max_shrinks = args.max_shrinks if args.max_shrinks is not None \
        else spec.count("worker_loss") + (1 if args.sdc else 0)
    env_extra = {
        "PADDLE_TPU_FAULT_SPEC": spec,
        "PADDLE_TPU_METRICS": "1",
        "PADDLE_TPU_METRICS_SINK": sink,
        # workers keep their own interval ledgers (goodput.* gauges in
        # the snap stream); the supervisor's JobLedger covers the
        # cross-incarnation gaps and lands in stats["goodput"]
        "PADDLE_TPU_GOODPUT": "1",
    }
    if args.sdc:
        # arm the sentinel in every worker: in-graph digests, replay
        # voting, and blame are all worker-side — the supervisor only
        # sees the resulting LOST exit
        env_extra["PADDLE_TPU_SDC"] = "1"
    if args.trace:
        # request tracing across the process boundary: supervise()
        # sees the flag in env_extra, opens one eager job trace, and
        # exports PADDLE_TPU_TRACE_ID to every incarnation — a
        # restarted worker's spans join the same trace (verdict below)
        env_extra["PADDLE_TPU_TRACE_SAMPLE"] = "1"
    if args.layout:
        env_extra["PADDLE_TPU_LAYOUT"] = "nhwc"
    if args.zero1:
        env_extra["PADDLE_TPU_ZERO"] = "1"
    if args.ckpt_replicas:
        env_extra["PADDLE_TPU_CKPT_REPLICAS"] = str(args.ckpt_replicas)
    worker_cmd = [os.path.abspath(__file__), "--worker",
                  "--steps", str(args.steps), "--result-dir", result_dir]
    if args.dispatch_steps > 1:
        # workers run the async dispatch window; the in-process parity
        # reference below stays synchronous (flag unset here), so
        # --check-parity proves faulted windowed == fault-free sync
        worker_cmd += ["--dispatch-steps", str(args.dispatch_steps)]
    if args.mesh:
        # every worker trains through the mesh-sharded executor path: a
        # dp mesh over 2 virtual devices, selected by the flag the
        # executor reads when no explicit mesh is passed. The override
        # (not setdefault) matters: the supervisor pinned its OWN
        # XLA_FLAGS to 1 device before initializing jax.
        env_extra["PADDLE_TPU_MESH"] = "dp=-1"
        env_extra["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        worker_cmd.append("--mesh")
    stats = {}
    rc = supervise(worker_cmd, nproc=args.nproc, env_extra=env_extra,
                   max_restarts=max_restarts, recovery_dir=ckpt_dir,
                   started_port=args.started_port,
                   heartbeat_ms=args.heartbeat_ms,
                   hang_timeout_s=args.hang_timeout,
                   max_shrinks=max_shrinks, stats=stats)
    obs.detach_sink()

    final_nproc = stats.get("final_nproc", args.nproc)
    verdict = {"spec": spec, "rc": rc, "workdir": workdir,
               "restarts": obs.snapshot()["counters"].get(
                   "recovery.restart", 0),
               "shrinks": stats.get("shrinks", 0),
               "final_nproc": final_nproc}
    problems = []
    if rc != 0:
        problems.append("gang failed with rc %s" % rc)
    # after a shrink only the SURVIVING ranks owe a full trajectory —
    # the lost rank is permanently gone by design
    ranks = {}
    for r in range(final_nproc):
        path = os.path.join(result_dir, "rank%d.json" % r)
        try:
            with open(path) as f:
                ranks[r] = json.load(f)
        except (OSError, ValueError) as e:
            problems.append("rank %d wrote no result (%s)" % (r, e))
            continue
        if len(ranks[r]) != args.steps:
            problems.append("rank %d finished %d/%d steps"
                            % (r, len(ranks[r]), args.steps))
    # the workers' telemetry sinks ARE the incident log: recoveries
    # must have been recorded there, not just survived. Per-worker
    # sinks are host-tagged (metrics.jsonl -> metrics.h<rank>.jsonl).
    recoveries = []
    sentinel_events = []
    trace_events = []
    for path in glob.glob(os.path.splitext(sink)[0] + "*"):
        with open(path) as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                name = str(ev.get("name", ""))
                if name.startswith(("recovery.", "faultinject",
                                    "health.", "ckpt.", "sentinel.")) \
                        and name != "ckpt.snapshot":
                    # ckpt.snapshot is routine save traffic, not an
                    # incident; the quorum/replica/poison events are
                    recoveries.append(name)
                if name.startswith("sentinel."):
                    sentinel_events.append(ev)
                if name.startswith("trace."):
                    trace_events.append(ev)
    verdict["recovery_events"] = sorted(set(recoveries))
    if spec and not recoveries and verdict["restarts"] == 0:
        problems.append("no recovery events recorded for spec %r" % spec)
    if "worker_hang" in spec and \
            "health.hang_detected" not in verdict["recovery_events"]:
        # the acceptance bar: the hang must be DETECTED from heartbeat
        # data, not merely survived by accident
        problems.append("spec injected worker_hang but the supervisor "
                        "never recorded health.hang_detected")
    if args.shrink or args.sdc:
        # the acceptance bar: the loss must have been ACTED on — the
        # supervisor recorded the shrink and the gang really is smaller
        if "health.mesh_shrunk" not in verdict["recovery_events"]:
            problems.append("the supervisor never recorded "
                            "health.mesh_shrunk")
        if final_nproc >= args.nproc:
            problems.append("the gang never shrank "
                            "(final nproc %d)" % final_nproc)
    if args.sdc:
        # the --sdc acceptance bar, end to end: the corruption must be
        # DETECTED at the injected step's retire (not later), the
        # replay vote must BLAME the injected rank, the transient must
        # have been absorbed, and the survivors' parity check below
        # proves the blamed rank's eviction cost zero trajectory drift
        by_name = {}
        for ev in sentinel_events:
            by_name.setdefault(ev["name"], []).append(
                ev.get("args") or {})
        suspects = by_name.get("sentinel.suspect", [])
        if not any(int(a.get("step", -1)) == sdc_persist
                   for a in suspects):
            problems.append(
                "no sentinel.suspect at injected engine step %d "
                "(suspects: %r)" % (sdc_persist, suspects))
        blamed = by_name.get("sentinel.blamed", [])
        if not any(int(a.get("step", -1)) == sdc_persist
                   and int(a.get("rank", -1)) == args.nproc - 1
                   for a in blamed):
            problems.append(
                "persistent bitflip on rank %d at engine step %d was "
                "never blamed (blamed: %r)"
                % (args.nproc - 1, sdc_persist, blamed))
        if not by_name.get("sentinel.transient"):
            problems.append("the transient bitflip on rank 0 was never "
                            "absorbed (no sentinel.transient event)")
        verdict["sentinel_events"] = sorted(by_name)
    if args.preempt:
        # the --preempt acceptance bar: the eviction was GRACEFUL (the
        # driver recorded recovery.preempted before exiting 46), the
        # supervisor took the no-budget restart path, and the restart
        # budget is untouched
        if "recovery.preempted" not in verdict["recovery_events"]:
            problems.append("rank 0 never recorded recovery.preempted")
        if "recovery.preempt_restart" not in verdict["recovery_events"]:
            problems.append("the supervisor never recorded "
                            "recovery.preempt_restart")
        verdict["preempts"] = stats.get("preempts", 0)
        if verdict["restarts"] != 0:
            problems.append(
                "preemption burned restart budget (recovery.restart "
                "= %d, expected 0)" % verdict["restarts"])
    if args.trace:
        # the --trace acceptance bar: ONE stitched trace spans the
        # whole chaosed job — the supervisor's trace ID was adopted by
        # every incarnation (spans from >= 2 distinct incarnations when
        # the gang restarted), with the supervisor's restart-gap span
        # between them. All reconstructed from the sinks alone.
        job_trace = stats.get("trace_id")
        verdict["trace_id"] = job_trace
        mine = [ev for ev in trace_events
                if (ev.get("args") or {}).get("trace") == job_trace]
        incs = sorted({(ev.get("args") or {}).get("incarnation")
                       for ev in mine
                       if (ev.get("args") or {}).get("incarnation")
                       is not None})
        names = sorted({str(ev.get("name", "")) for ev in mine})
        verdict["trace"] = {"spans": len(mine), "incarnations": incs,
                            "names": names}
        if not job_trace:
            problems.append("supervise() opened no job trace "
                            "(stats carries no trace_id)")
        elif not mine:
            problems.append("no trace.* spans for job trace %s in the "
                            "sinks" % job_trace)
        else:
            if verdict["restarts"] > 0 and len(incs) < 2:
                problems.append(
                    "gang restarted but the job trace has spans from "
                    "incarnation(s) %r only — the respawned worker "
                    "never joined the trace" % (incs,))
            if verdict["restarts"] > 0 \
                    and "trace.restart" not in names:
                problems.append("job trace has no supervisor "
                                "trace.restart span covering the gap")
            if "trace.train_start" not in names:
                problems.append("no worker ever adopted the job trace "
                                "(missing trace.train_start)")
    # goodput attribution gate: the supervisor's job ledger must (a)
    # conserve — categories sum to wall clock within 1% — and (b) have
    # charged the injected fault's cost to the RIGHT badput category,
    # not diffused it into idle
    job = stats.get("goodput") or {}
    cats = job.get("categories") or {}
    verdict["goodput"] = {
        "wall_ms": round(job.get("wall_ms", 0.0), 1),
        "goodput_frac": round(job.get("goodput_frac", 0.0), 4),
        "categories": {c: round(m, 1) for c, m in cats.items() if m > 0},
    }
    badput = {c: m for c, m in cats.items()
              if c not in ("compute", "input_wait", "host_sync")
              and m > 0}
    verdict["goodput_attr"] = (
        "%s:%.0fms" % max(badput.items(), key=lambda cm: cm[1])
        if badput else "clean")
    wall = job.get("wall_ms", 0.0)
    if not cats:
        problems.append("the supervisor recorded no job goodput ledger")
    elif wall > 0:
        err = abs(sum(cats.values()) - wall) / wall
        if err > 0.01:
            problems.append(
                "job ledger does not conserve: categories sum to "
                "%.1fms over %.1fms wall (err %.2f%%)"
                % (sum(cats.values()), wall, 100.0 * err))
    if verdict["restarts"] > 0 and not cats.get("restart_downtime"):
        problems.append("gang restarted %d time(s) but the job ledger "
                        "charged no restart_downtime"
                        % verdict["restarts"])
    if args.preempt and not cats.get("preempt_drain"):
        problems.append("preemption gate but the job ledger charged "
                        "no preempt_drain")
    if (args.shrink or args.sdc) and stats.get("shrinks", 0) > 0 \
            and not cats.get("shrink_rejit"):
        problems.append("the gang shrank but the job ledger charged "
                        "no shrink_rejit")
    if args.check_parity and not problems:
        import numpy as np

        for r, got in ranks.items():
            want = train_losses(args.steps,
                                os.path.join(workdir, "ref%d" % r), rank=r)
            # the supervisor's in-process reference runs single-device /
            # no-mesh: under --mesh the workers' psum reduction order
            # differs from the one-device sum, so parity is allclose
            # there and bit-exact otherwise
            ok = (np.allclose(got, want, rtol=1e-5, atol=1e-7)
                  if args.mesh else got == want)
            if not ok:
                diff = next(i for i, (a, b) in enumerate(zip(got, want))
                            if a != b)
                problems.append(
                    "rank %d diverged from the fault-free run at step %d"
                    % (r, diff))
    verdict["ok"] = not problems
    if problems:
        verdict["problems"] = problems
    print(json.dumps(verdict))
    return 0 if not problems else 1


def run_serve_retry(args):
    """Serving-fleet worker-kill-mid-flight gate (--serve-retry).

    Two in-process ``InferenceServer`` workers over ONE frozen program
    behind a ``FleetRouter`` with the full protection envelope (bounded
    retries, a hedge timer, per-worker circuit breakers) and request
    tracing at sample rate 1.0. The gate injects faults into worker 0's
    device-dispatch seam (``_run_padded``) and asserts the router's
    graceful-degradation story end to end:

    * hedge — worker 0 made a 0.5s straggler: the hedge timer re-issues
      on worker 1, the hedge wins, the client still gets the correct
      answer, and the cancelled straggler must NOT poison worker 0's
      batcher (the collect loop drops claimed-dead futures);
    * retry — worker 0 killed mid-flight: every routed request still
      resolves with the bit-correct result via worker 1; the failed and
      relaunched attempts share ONE trace id, and the stitched trace
      shows route spans on BOTH workers plus the ``trace.retry``
      hand-off span; two consecutive failures trip worker 0's breaker;
    * recover — fault cleared: after the breaker cooldown a half-open
      probe routes one real request to worker 0, its success closes the
      breaker, and worker 0 serves traffic again.

    Prints the machine verdict as the last stdout line.
    """
    import time

    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_query
    from serve_probe import build_server

    import paddle_tpu.fluid as fluid
    from paddle_tpu import flags
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import InferenceServer
    from paddle_tpu.resilience.elastic import FleetRouter

    workdir = args.workdir or tempfile.mkdtemp(prefix="serve_retry_")
    os.makedirs(workdir, exist_ok=True)
    sink = os.path.join(workdir, "events.jsonl")
    problems = []
    obs.set_enabled(True)
    obs.reset()
    flags.set_flags({"metrics": True, "trace_sample": 1.0,
                     "trace_buffer": 16384})
    obs.attach_sink(sink)
    try:
        s0, one_row, _ = build_server(
            "mlp", int8=False, buckets="1,2", max_wait_ms=5.0,
            seed=args.seed)
        # the second worker wraps the SAME frozen program + scope: both
        # workers are bit-identical replicas, so "the survivor answered
        # correctly" is checkable against one executor reference
        s1 = InferenceServer(s0.program, s0.feed_names, s0.fetch_names,
                             scope=s0.scope, executor=s0._exe,
                             buckets=(1, 2), max_wait_ms=5.0,
                             name="probe-1")

        # fault seam on worker 0's device dispatch
        state = {"fail": False, "slow_s": 0.0, "served": 0, "fails": 0}
        orig_run = s0._run_padded

        def poisoned(feed, bucket):
            if state["slow_s"]:
                time.sleep(state["slow_s"])
            if state["fail"]:
                state["fails"] += 1
                raise RuntimeError("injected device loss (chaos)")
            out = orig_run(feed, bucket)
            state["served"] += 1
            return out

        s0._run_padded = poisoned

        rng = np.random.RandomState(args.seed)
        feeds = [{"img": rng.randn(1, 784).astype(np.float32)}
                 for _ in range(40)]
        with fluid.scope_guard(s0.scope):
            expected = [np.asarray(s0._exe.run(
                s0.program, feed=f,
                fetch_list=list(s0.fetch_names))[0]) for f in feeds]

        router = FleetRouter(lambda idx: (s0, s1)[idx], min_workers=2,
                             max_workers=2, cooldown_s=3600.0,
                             retries=2, hedge_after_ms=150.0,
                             breaker_failures=2, breaker_reset_s=1.0)
        router.start()
        try:
            for srv in (s0, s1):
                srv.warmup(feeds[0])

            def drain(lo, hi, phase):
                futs = [(i, router.submit(feeds[i]))
                        for i in range(lo, hi)]
                tids = []
                for i, f in futs:
                    try:
                        got = f.result(timeout=60)
                    except Exception as e:  # noqa: BLE001
                        problems.append("%s: request %d failed: %r"
                                        % (phase, i, e))
                        continue
                    tids.append(getattr(f, "trace_id", None))
                    if not np.allclose(np.asarray(got[0]), expected[i],
                                       rtol=1e-5, atol=1e-5):
                        problems.append("%s: request %d answered "
                                        "incorrectly" % (phase, i))
                return tids

            # -- phase 0: healthy fleet baseline
            drain(0, 6, "healthy")

            # -- phase 1: straggler -> hedge wins, answer still right
            state["slow_s"] = 0.5
            drain(6, 12, "hedge")
            state["slow_s"] = 0.0
            if router.hedge_wins < 1:
                problems.append("0.5s straggler never lost to a hedge "
                                "(hedges=%d wins=%d)"
                                % (router.hedges, router.hedge_wins))
            time.sleep(0.8)     # let worker 0 drain cancelled losers
            if not s0.alive():
                problems.append("worker 0's dispatch loop died on a "
                                "cancelled hedge loser")

            # -- phase 2: kill worker 0 mid-flight -> retries + breaker
            state["fail"] = True
            retries_before = router.retries
            kill_tids = drain(12, 26, "kill")
            stats = router.stats()
            if router.retries <= retries_before:
                problems.append("worker kill produced no retries")
            if stats["breaker_trips"] < 1:
                problems.append("repeated failures never tripped the "
                                "breaker: %s" % stats)
            if stats["breakers_open"] < 1:
                problems.append("breaker not open right after the kill "
                                "phase: %s" % stats)
            served_sick = state["served"]

            # -- phase 3: clear the fault -> half-open probe recovers
            state["fail"] = False
            time.sleep(1.2)     # past breaker_reset_s
            drain(26, 40, "recover")
            stats = router.stats()
            if stats["breakers_open"] != 0:
                problems.append("breaker still open after recovery: %s"
                                % stats)
            if state["served"] <= served_sick:
                problems.append("worker 0 never served again after the "
                                "fault cleared")
            fleet = {"retries": router.retries, "hedges": router.hedges,
                     "hedge_wins": router.hedge_wins,
                     "breaker_trips": stats["breaker_trips"],
                     "worker0_served": state["served"],
                     "worker0_fails": state["fails"]}
        finally:
            router.stop()
    finally:
        obs.detach_sink()
        for name in ("trace_sample", "trace_buffer", "metrics"):
            flags.reset_flag(name)
        obs.set_enabled(None)
        obs.reset()

    # -- stitched-trace audit: the retried request is ONE trace showing
    # the failed attempt, the hand-off, and the serving attempt
    traces, _, _ = trace_query.load([sink])
    retry_traces = {tid: evs for tid, evs in traces.items()
                    if any(ev["name"] == "trace.retry" for ev in evs)}
    stitched = 0
    for tid, evs in retry_traces.items():
        workers = {ev["args"].get("worker") for ev in evs
                   if ev["name"] == "trace.route"}
        errored = any(ev["name"] == "trace.request"
                      and ev["args"].get("error") for ev in evs)
        served = any(ev["name"] == "trace.request"
                     and not ev["args"].get("error") for ev in evs)
        if len(workers) >= 2 and errored and served:
            stitched += 1
    if not retry_traces:
        problems.append("no trace carries a trace.retry span")
    elif stitched == 0:
        problems.append("retry traces exist but none stitches both "
                        "attempts (route spans on 2 workers + errored "
                        "and served request spans) under one id")
    if kill_tids and not (set(retry_traces) & set(kill_tids)):
        problems.append("retry spans landed outside the kill-phase "
                        "trace ids")

    verdict = {
        "gate": "serve_retry",
        "fleet": fleet,
        "traces": {"total": len(traces), "retry": len(retry_traces),
                   "stitched": stitched},
        "sink": sink,
        "ok": not problems,
    }
    if problems:
        verdict["problems"] = problems
    print(json.dumps(verdict))
    return 0 if not problems else 1


def main():
    parser = argparse.ArgumentParser("chaos_run")
    parser.add_argument("--worker", action="store_true",
                        help="internal: run as one supervised worker")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--nproc", type=int, default=2)
    parser.add_argument("--seed", type=int, default=0,
                        help="fault-schedule seed (same seed, same chaos)")
    parser.add_argument("--spec", default=None,
                        help="explicit fault spec; overrides --seed")
    parser.add_argument("--max-restarts", type=int, default=None,
                        help="default: worker kills/hangs in the spec + 1")
    parser.add_argument("--shrink", action="store_true",
                        help="inject a PERMANENT worker loss (rc 45) on "
                             "the highest rank mid-run: the supervisor "
                             "must shrink the gang and the survivors "
                             "must finish every step with fault-free "
                             "parity")
    parser.add_argument("--max-shrinks", type=int, default=None,
                        help="elastic shrink budget for the supervisor "
                             "(default: worker_loss entries in the spec, "
                             "+1 under --sdc)")
    parser.add_argument("--sdc", action="store_true",
                        help="silent-data-corruption gate: workers run "
                             "with PADDLE_TPU_SDC=1; a transient bitflip "
                             "on rank 0 must be replay-absorbed and a "
                             "persistent one on the highest rank must be "
                             "blamed, quarantined via gang shrink, and "
                             "the survivors must keep bit-exact "
                             "fault-free parity")
    parser.add_argument("--preempt", action="store_true",
                        help="graceful-preemption gate: rank 0 is "
                             "SIGTERM-evicted mid-run, must drain + "
                             "checkpoint + exit rc 46, and the "
                             "supervisor must restart without spending "
                             "restart budget")
    parser.add_argument("--ckpt-replicas", type=int, default=0,
                        help="mirror each rank's checkpoint shards into "
                             "this many PEER ranks' roots (quorum "
                             "restore coverage; pairs with a disk_fail "
                             "spec entry)")
    parser.add_argument("--trace", action="store_true",
                        help="cross-process tracing gate: the "
                             "supervisor opens one job trace, every "
                             "incarnation adopts it via "
                             "PADDLE_TPU_TRACE_ID, and the verdict "
                             "asserts one stitched trace spanning both "
                             "incarnations of a killed worker with the "
                             "supervisor's restart span between")
    parser.add_argument("--hang", action="store_true",
                        help="seeded spec injects worker_hang instead of "
                             "worker_kill — exercises the heartbeat "
                             "watchdog rather than the exit-code path")
    parser.add_argument("--heartbeat-ms", type=float, default=200.0,
                        help="worker heartbeat interval under supervise")
    parser.add_argument("--hang-timeout", type=float, default=15.0,
                        help="seconds of step-counter stall before the "
                             "supervisor declares a rank hung (must "
                             "comfortably exceed worker startup + first "
                             "XLA compile, which the stall clock ticks "
                             "through)")
    parser.add_argument("--workdir", default=None,
                        help="default: fresh temp dir, kept for forensics")
    parser.add_argument("--result-dir", default=None)
    parser.add_argument("--started_port", type=int, default=6280)
    parser.add_argument("--dispatch-steps", type=int, default=1,
                        help="workers enqueue this many steps into the "
                             "engine's async dispatch window "
                             "(engine/pipeline.py) — injected faults "
                             "land mid-window and must still restore "
                             "to bit-exact parity with the synchronous "
                             "fault-free reference")
    parser.add_argument("--mesh", action="store_true",
                        help="workers train through the dp-mesh GSPMD "
                             "path (2 virtual devices each) — proves the "
                             "mesh data-parallel path survives "
                             "worker_kill under the gang supervisor")
    parser.add_argument("--layout", action="store_true",
                        help="run everything with PADDLE_TPU_LAYOUT=nhwc "
                             "and a conv stem on the probe model: the "
                             "NHWC pass rewrites the step, the filter is "
                             "baked HWIO into the checkpointed scope, "
                             "and restart/rollback must still replay to "
                             "bit-exact fault-free parity")
    parser.add_argument("--zero1", action="store_true",
                        help="run everything with PADDLE_TPU_ZERO=1 and "
                             "a Momentum probe optimizer: the workers' "
                             "dp-mesh update is ZeRO-1 sharded (velocity "
                             "slots partitioned, params all-gathered "
                             "after the shard update) and every "
                             "recovery path — restart, shrink, replay — "
                             "must keep fault-free parity with the "
                             "sharded state migrating across meshes")
    parser.add_argument("--serve-retry", action="store_true",
                        help="run the in-process serving-fleet gate "
                             "instead of the training gang: kill a "
                             "fleet worker mid-flight and assert hedged "
                             "retries answer correctly under one "
                             "stitched trace, the sick worker's breaker "
                             "trips, and a half-open probe recovers it")
    parser.add_argument("--check-parity", action="store_true",
                        default=True)
    parser.add_argument("--no-check-parity", dest="check_parity",
                        action="store_false")
    args = parser.parse_args()
    if args.layout:
        # in os.environ (not just env_extra) so the supervisor's OWN
        # in-process parity reference builds the same conv probe and
        # runs the same NHWC-rewritten executable as the workers
        os.environ["PADDLE_TPU_LAYOUT"] = "nhwc"
    if args.zero1:
        # same reasoning: the parity reference must build the Momentum
        # probe; the zero flag itself is inert there (no mesh)
        os.environ["PADDLE_TPU_ZERO"] = "1"
    if args.worker:
        return run_worker(args)
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=1")
    import jax

    jax.config.update("jax_platforms", "cpu")
    if args.serve_retry:
        return run_serve_retry(args)
    return run_supervisor(args)


if __name__ == "__main__":
    sys.exit(main())
