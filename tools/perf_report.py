#!/usr/bin/env python
"""Merge a host-span dump with xplane device aggregates into one
per-step perf report — or merge a directory of per-worker JSONL
telemetry dumps into one cross-host report.

Single-host mode: the host side comes from
``observability.dump_chrome_trace(path)`` (or the
``<profile_path>.trace.json`` stop_profiler writes): every engine step
is a "step" slice with its trace/transform/lower/compile/run children.
The device side comes from the jax profiler's xplane dump, aggregated
per op by tools/xplane_top_ops.py. Together they answer the question
the throughput number alone cannot: where did each step's wall time go
— host build (trace/transform/lower), XLA compile, dispatch, or device
kernels.

Multi-host mode (``--merge DIR``): DIR holds the host-tagged JSONL
sinks each worker streamed (``PADDLE_TPU_METRICS_SINK`` +
distributed/launch.py's per-rank tagging — ``<base>.h<rank>.jsonl``
plus rotations). The merge joins them on step number into the table a
pod run is debugged from: per-step latency skew across workers,
slowest-worker attribution, per-worker heartbeat ages (which rank went
quiet or stalled first), and each worker's aggregate HBM watermarks.

Usage:
    PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \\
        python tools/perf_report.py HOST_TRACE.json [XPLANE_DIR] [--top N]
    python tools/perf_report.py --merge DUMP_DIR

With no XPLANE_DIR (or without the xplane protos installed) the report
is host-only.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), os.pardir)))

# The per-step breakdown columns, in pipeline order. "other" is the
# step-slice remainder not covered by any of them.
PHASES = ("trace", "transform", "lower", "compile", "run")


def load_host_events(path):
    with open(path) as f:
        trace = json.load(f)
    return [e for e in trace.get("traceEvents", [])
            if e.get("ph") == "X"]


def per_step_rows(events):
    """Group host slices into steps: each "step" slice owns every slice
    nested inside its [ts, ts+dur) window on the same pid/tid."""
    steps = sorted((e for e in events if e["name"] == "step"),
                   key=lambda e: e["ts"])
    rows = []
    for i, st in enumerate(steps):
        t0, t1 = st["ts"], st["ts"] + st.get("dur", 0.0)
        row = {"step": st.get("args", {}).get("step", i + 1),
               "total_ms": st.get("dur", 0.0) / 1e3}
        for ph in PHASES:
            row[ph] = 0.0
        for e in events:
            if e is st or e.get("pid") != st.get("pid") \
                    or e.get("tid") != st.get("tid"):
                continue
            if e["name"] in PHASES and t0 <= e["ts"] < t1:
                row[e["name"]] += e.get("dur", 0.0) / 1e3
        row["other"] = max(0.0, row["total_ms"] - sum(
            row[ph] for ph in PHASES))
        rows.append(row)
    return rows


def render_host(rows):
    lines = ["== host: per-step wall (ms) =="]
    hdr = ("step", "total") + PHASES + ("other",)
    lines.append("  ".join("%9s" % h for h in hdr))
    for r in rows:
        lines.append("  ".join(
            ["%9s" % r["step"], "%9.2f" % r["total_ms"]]
            + ["%9.2f" % r[ph] for ph in PHASES]
            + ["%9.2f" % r["other"]]))
    if not rows:
        lines.append("(no step spans in the host dump — was "
                     "PADDLE_TPU_METRICS up?)")
    return "\n".join(lines)


def render_device(xplane_dir, top_n):
    from paddle_tpu.observability.opprof import top_ops

    rows, total = top_ops(xplane_dir, top_n=top_n)
    lines = ["", "== device: XLA-op time (total %.2f ms) ==" % total]
    for name, ms in rows:
        pct = (ms / total * 100) if total else 0.0
        lines.append("%10.3f ms  %5.1f%%  %s" % (ms, pct, name[:80]))
    return "\n".join(lines)


def render_roofline(table, top_n):
    """The per-op roofline table from an attribution result: top-k by
    device time with %-of-step, arithmetic intensity (FLOPs/byte), the
    compute/memory/comm-bound verdict, and the source-op list fused ops
    expand to."""
    from paddle_tpu.observability import opprof

    lines = [
        "== roofline: device time by framework op "
        "(source %s, fusion policy %s) =="
        % (table["source"], table["fusion_policy"]),
        "%-36s %10s %6s %10s %-13s %s"
        % ("op", "ms", "%", "FLOP/B", "verdict", "src_ops")]
    shown = 0
    for tag, row in opprof.top_rows(table, top_n):
        if row["ms"] <= 0:
            continue
        shown += 1
        lines.append(
            "%-36s %10.3f %5.1f%% %10.2f %-13s %s"
            % (tag[:36], row["ms"], 100.0 * row["frac"],
               row["intensity"], row["verdict"],
               ",".join(row["src_ops"])[:40]))
    if not shown:
        lines.append("(no device time attributed to any provenance tag "
                     "— was the trace taken with PADDLE_TPU_OPPROF on?)")
    zero = [t for t, r in table["ops"].items() if r["ms"] <= 0]
    if zero:
        lines.append("(+%d op(s) at 0 ms: fused away or constant-folded "
                     "— e.g. %s)" % (len(zero), ", ".join(zero[:4])))
    lines.append(
        "attributed %.1f%% of %.3f ms device time "
        "(unattributed %.3f ms, comm lane %.3f ms, %d/%d collective "
        "instruction(s) vs registered schedule)"
        % (100.0 * table["attributed_frac"], table["total_ms"],
           table["unattributed_ms"], table["comm_ms"],
           table["collective_instances"],
           table["expected_collective_instances"]))
    if table["source"] != "tpu":
        lines.append("NOTE: CPU-plane attribution is coarse (durations "
                     "include host dispatch) — verdicts are "
                     "hardware-trustworthy on TPU traces only")
    return "\n".join(lines)


def roofline_report(xplane_dir, top_n=15, gate=False):
    """-> (text, rc). Attribute the trace dir's device time per
    provenance tag (using the opprof_provenance.json sidecar
    stop_profiler wrote next to the xplane dumps) and render the
    roofline table. With ``gate`` the rc is nonzero when the table is
    empty or the collective lane disagrees with the registered HLO
    schedule — wire into the bench flow the way multichip_probe
    --predict is."""
    from paddle_tpu.observability import opprof

    try:
        table = opprof.attribute(xplane_dir)
    except Exception as e:
        text = "roofline: attribution failed: %s" % e
        return text, (1 if gate else 0)
    text = render_roofline(table, top_n)
    rc = 0
    if gate:
        issues = opprof.gate_issues(table)
        for issue in issues:
            text += "\nGATE: %s" % issue
        rc = 1 if issues else 0
        if not issues:
            text += "\nroofline gate: PASS"
    return text, rc


# -- multi-host merge ------------------------------------------------------

# The HBM watermark gauges a "snap" event carries, in report order.
HBM_GAUGES = ("hbm.live_bytes_peak", "hbm.compile_peak_bytes",
              "hbm.device_peak_bytes_in_use")


def load_worker_dumps(dump_dir):
    """Parse every JSONL sink file under ``dump_dir`` (live + rotated),
    grouped by the host id each event carries:
    ``{host: {"steps": {step: dur_ms}, "hbm": {gauge: max_bytes},
    "hb": {count, last_ts, last_step, step_ts}, "files": [...],
    "events": n, "last_ts": newest_event_us}}``. The ``hb`` record
    tracks the newest ``health.heartbeat`` per worker so the merged
    report can show which rank went quiet (or stalled) first."""
    from paddle_tpu.observability.export import iter_events, sink_file_set
    from paddle_tpu.observability.health import HEARTBEAT_EVENT

    workers = {}

    def w(host):
        return workers.setdefault(
            host, {"steps": {}, "hbm": {}, "goodput": {}, "opprof": {},
                   "exemplars": {}, "job": None,
                   "hb": {"count": 0, "last_ts": None, "last_step": None,
                          "step_ts": None},
                   "files": set(), "events": 0, "last_ts": None})

    for path in sink_file_set(dump_dir):
        for ev in iter_events(path):
            host = ev.get("host", 0)
            rec = w(host)
            rec["files"].add(os.path.basename(path))
            rec["events"] += 1
            ts = ev.get("ts")
            if ts is not None:
                rec["last_ts"] = ts if rec["last_ts"] is None \
                    else max(rec["last_ts"], ts)
            kind = ev.get("t")
            if kind == "span" and ev.get("name") == "step":
                step = (ev.get("args") or {}).get("step")
                if step is not None:
                    # keep the LAST duration per step number (restarted
                    # counters: later wins, matching the file order)
                    rec["steps"][int(step)] = ev.get("dur", 0.0) / 1e3
            elif kind == "span" and ev.get("name") == HEARTBEAT_EVENT:
                hb = rec["hb"]
                hb["count"] += 1
                if ts is not None and (hb["last_ts"] is None
                                       or ts >= hb["last_ts"]):
                    hb["last_ts"] = ts
                    step = (ev.get("args") or {}).get("step")
                    if step is not None and step != hb["last_step"]:
                        hb["last_step"] = step
                        hb["step_ts"] = ts
            elif kind == "span" and ev.get("name") == "goodput.job":
                # the supervisor's job-ledger event (one per job exit);
                # later wins, matching file order
                rec["job"] = ev.get("args") or {}
            elif kind == "snap":
                gauges = (ev.get("metrics") or {}).get("gauges") or {}
                for g in HBM_GAUGES:
                    v = gauges.get(g)
                    if v is not None:
                        rec["hbm"][g] = max(rec["hbm"].get(g, 0), int(v))
                for g, v in gauges.items():
                    # goodput/mfu gauges are running totals, not
                    # watermarks: keep the NEWEST value per host
                    if g.startswith("goodput.") or g.startswith("mfu."):
                        rec["goodput"][g] = v
                    elif g.startswith("opprof."):
                        # per-op device-time gauges stop_profiler set —
                        # newest wins (they summarize the whole session)
                        rec["opprof"][g] = v
                ex = (ev.get("metrics") or {}).get("exemplars") or {}
                # exemplar slots pin the trace id of the worst request
                # behind each latency series — newest snapshot wins
                rec["exemplars"].update(ex)
    for rec in workers.values():
        rec["files"] = sorted(rec["files"])
    return workers


def _fmt_bytes(n):
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return ("%.1f %s" % (n, unit)) if unit != "B" \
                else ("%d B" % n)
        n /= 1024.0
    return "%d" % n


def render_merge(workers):
    """The cross-host report: step-skew table, slowest-worker
    attribution, worker heartbeat health, aggregate HBM watermarks."""
    hosts = sorted(workers)
    lines = ["== cross-host: per-step wall (ms) across %d worker(s) =="
             % len(hosts)]
    if not hosts:
        lines.append("(no worker dumps found — were sinks attached via "
                     "PADDLE_TPU_METRICS_SINK?)")
        return "\n".join(lines)
    all_steps = sorted({s for h in hosts for s in workers[h]["steps"]})
    hdr = ["step"] + ["h%s" % h for h in hosts] + ["skew", "slowest"]
    lines.append("  ".join("%9s" % c for c in hdr))
    slowest_count = dict.fromkeys(hosts, 0)
    for step in all_steps:
        durs = {h: workers[h]["steps"].get(step) for h in hosts}
        present = {h: d for h, d in durs.items() if d is not None}
        row = ["%9d" % step]
        for h in hosts:
            row.append("%9.2f" % durs[h] if durs[h] is not None
                       else "%9s" % "-")
        if present:
            skew = max(present.values()) - min(present.values())
            slow = max(present, key=present.get)
            slowest_count[slow] += 1
            row += ["%9.2f" % skew, "%9s" % ("h%s" % slow)]
        else:
            row += ["%9s" % "-", "%9s" % "-"]
        lines.append("  ".join(row))
    if all_steps:
        joined = [s for s in all_steps
                  if all(s in workers[h]["steps"] for h in hosts)]
        if joined:
            skews = [max(workers[h]["steps"][s] for h in hosts)
                     - min(workers[h]["steps"][s] for h in hosts)
                     for s in joined]
            lines.append(
                "steps joined across all workers: %d  mean skew: %.2f ms"
                "  max skew: %.2f ms"
                % (len(joined), sum(skews) / len(skews), max(skews)))
        attribution = ", ".join(
            "h%s %d/%d" % (h, slowest_count[h], len(all_steps))
            for h in hosts if slowest_count[h])
        if attribution:
            lines.append("slowest-worker attribution: " + attribution)
    if any(workers[h]["hb"]["count"] for h in hosts):
        # heartbeat ages are measured against the FLEET's newest event:
        # in a post-mortem dump "now" is whenever the job died, and the
        # rank whose age stands out is the one that went quiet first
        fleet_end = max(workers[h]["last_ts"] for h in hosts
                        if workers[h]["last_ts"] is not None)
        lines.append("")
        lines.append("== worker health (heartbeat ages vs fleet end) ==")
        hdr = ("host", "beats", "last_step", "hb_age_s", "stalled_s")
        lines.append("  ".join("%10s" % c for c in hdr))
        for h in hosts:
            hb = workers[h]["hb"]
            age = (fleet_end - hb["last_ts"]) / 1e6 \
                if hb["last_ts"] is not None else None
            stalled = (hb["last_ts"] - hb["step_ts"]) / 1e6 \
                if hb["last_ts"] is not None and hb["step_ts"] is not None \
                else None
            lines.append("  ".join([
                "%10s" % ("h%s" % h),
                "%10d" % hb["count"],
                "%10s" % (hb["last_step"]
                          if hb["last_step"] is not None else "-"),
                "%10s" % ("%.1f" % age if age is not None else "-"),
                "%10s" % ("%.1f" % stalled
                          if stalled is not None else "-")]))
    lines.append("")
    lines.append("== aggregate HBM watermarks ==")
    short = {g: g[len("hbm."):] for g in HBM_GAUGES}
    hdr = ["host"] + [short[g] for g in HBM_GAUGES] + ["events", "files"]
    lines.append("  ".join("%24s" % c if i else "%6s" % c
                           for i, c in enumerate(hdr)))
    fleet = {}
    for h in hosts:
        rec = workers[h]
        row = ["%6s" % ("h%s" % h)]
        for g in HBM_GAUGES:
            v = rec["hbm"].get(g)
            if v is not None:
                fleet[g] = max(fleet.get(g, 0), v)
            row.append("%24s" % _fmt_bytes(v))
        row.append("%24d" % rec["events"])
        row.append("  " + ",".join(rec["files"]))
        lines.append("  ".join(row))
    if fleet:
        lines.append("fleet max: " + "  ".join(
            "%s=%s" % (short[g], _fmt_bytes(fleet[g]))
            for g in HBM_GAUGES if g in fleet))
    hot = render_fleet_hot_ops(workers)
    if hot:
        lines.append("")
        lines.append(hot)
    ex = render_exemplars(workers)
    if ex:
        lines.append("")
        lines.append(ex)
    return "\n".join(lines)


def render_exemplars(workers):
    """The metric→trace exemplar table: for each host that streamed
    exemplar slots in its metric snapshots, the offending request's
    trace id and the value it pinned — the lookup key for
    ``tools/trace_query.py --trace ID``. Returns "" when no worker
    carried exemplars."""
    hosts = [h for h in sorted(workers) if workers[h]["exemplars"]]
    if not hosts:
        return ""
    lines = ["== metric exemplars (worst request per series — "
             "tools/trace_query.py --trace ID) =="]
    hdr = ("host", "metric", "value", "trace")
    lines.append("  ".join(["%6s" % hdr[0], "%-28s" % hdr[1],
                            "%12s" % hdr[2], hdr[3]]))
    for h in hosts:
        for metric in sorted(workers[h]["exemplars"]):
            slot = workers[h]["exemplars"][metric] or {}
            val = slot.get("value")
            lines.append("  ".join([
                "%6s" % ("h%s" % h),
                "%-28s" % metric[:28],
                "%12s" % ("%.3f" % val if isinstance(val, (int, float))
                          else "-"),
                str(slot.get("trace_id", "-"))]))
    return "\n".join(lines)


def render_fleet_hot_ops(workers, top_n=10):
    """The fleet hot-ops table: per provenance tag, each rank's device
    ms (from the ``opprof.<tag>_ms`` gauges stop_profiler streams into
    the sink) plus the cross-rank spread — so a straggler is
    attributable to an OP, not just a rank. Returns "" when no worker
    carried opprof gauges."""
    hosts = sorted(workers)
    per_tag = {}  # tag -> {host: ms}
    for h in hosts:
        for g, v in workers[h]["opprof"].items():
            if not g.endswith("_ms") or not g.startswith("opprof.pt."):
                continue
            tag = g[len("opprof."):-len("_ms")]
            per_tag.setdefault(tag, {})[h] = float(v)
    if not per_tag:
        return ""
    lines = ["== fleet hot ops (device ms per rank, opprof tags) =="]
    hdr = ["op"] + ["h%s" % h for h in hosts] + ["spread"]
    lines.append("%-36s" % hdr[0] + "  ".join("%9s" % c
                                              for c in hdr[1:]))
    ranked = sorted(per_tag.items(),
                    key=lambda kv: -max(kv[1].values()))[:top_n]
    for tag, per_host in ranked:
        vals = [per_host.get(h) for h in hosts]
        present = [v for v in vals if v is not None]
        spread = (max(present) - min(present)) if len(present) > 1 \
            else 0.0
        lines.append("%-36s" % tag[:36] + "  ".join(
            ("%9.3f" % v) if v is not None else "%9s" % "-"
            for v in vals) + "  %9.3f" % spread)
    fracs = [workers[h]["opprof"].get("opprof.attributed_frac")
             for h in hosts]
    if any(f is not None for f in fracs):
        lines.append("attributed frac per rank: " + "  ".join(
            "h%s=%.1f%%" % (h, 100.0 * f) for h, f in zip(hosts, fracs)
            if f is not None))
    return "\n".join(lines)


def render_goodput(workers):
    """The fleet badput-attribution report: per-rank goodput %, MFU,
    and slowest badput category from each rank's ``goodput.*``/``mfu.*``
    gauges, the fleet-weighted goodput %, and the supervisor's
    cross-incarnation job ledger (the ``goodput.job`` event) — where
    restart backoff, shrink re-plans, and preemption drains live."""
    from paddle_tpu.observability.goodput import (CATEGORIES,
                                                  GOODPUT_CATEGORIES)

    hosts = sorted(workers)
    lines = ["== fleet goodput / badput attribution =="]
    rows = []
    for h in hosts:
        g = workers[h]["goodput"]
        if not g:
            continue
        cats = {c: float(g.get("goodput.%s_ms" % c, 0.0))
                for c in CATEGORIES}
        bad = sorted(((c, m) for c, m in cats.items()
                      if c not in GOODPUT_CATEGORIES and m > 0),
                     key=lambda cm: -cm[1])
        rows.append({
            "host": h,
            "wall": float(g.get("goodput.wall_ms", 0.0)),
            "frac": g.get("goodput.frac"),
            "mfu": g.get("mfu.mfu"),
            "flops_s": g.get("mfu.achieved_flops_per_s"),
            "top": ("%s %.0fms" % bad[0]) if bad else "-",
            "good": sum(cats[c] for c in GOODPUT_CATEGORIES),
        })
    if rows:
        hdr = ("host", "wall_s", "goodput%", "mfu%", "flops/s",
               "top badput")
        lines.append("  ".join("%10s" % c for c in hdr))
        for r in rows:
            lines.append("  ".join([
                "%10s" % ("h%s" % r["host"]),
                "%10.2f" % (r["wall"] / 1e3),
                "%10s" % ("%.2f" % (100.0 * r["frac"])
                          if r["frac"] is not None else "-"),
                "%10s" % ("%.1f" % (100.0 * r["mfu"])
                          if r["mfu"] else "-"),
                "%10s" % ("%.3g" % r["flops_s"]
                          if r["flops_s"] else "-"),
                "  " + r["top"]]))
        fleet_wall = sum(r["wall"] for r in rows)
        fleet_good = sum(r["good"] for r in rows)
        if fleet_wall > 0:
            lines.append("fleet goodput: %.2f%% over %.1f s of rank wall"
                         % (100.0 * fleet_good / fleet_wall,
                            fleet_wall / 1e3))
    else:
        lines.append("(no per-rank goodput gauges — was "
                     "PADDLE_TPU_GOODPUT=1 exported to the workers?)")
    for h in hosts:
        job = workers[h]["job"]
        if not job:
            continue
        cats = job.get("categories") or {}
        bad = sorted(((c, float(m)) for c, m in cats.items()
                      if c not in GOODPUT_CATEGORIES and float(m) > 0),
                     key=lambda cm: -cm[1])
        lines.append("")
        lines.append("== supervisor job ledger (host %s) ==" % h)
        lines.append("wall: %.1f s  goodput: %.2f%%  incarnations: %s"
                     % (float(job.get("wall_ms", 0.0)) / 1e3,
                        100.0 * float(job.get("goodput_frac", 0.0)),
                        1 + int(job.get("attempt", 0))))
        for c, m in bad:
            lines.append("  %-18s %10.1f ms" % (c, m))
        if not bad:
            lines.append("  (no cross-incarnation badput)")
    return "\n".join(lines)


def goodput_report(dump_dir):
    return render_goodput(load_worker_dumps(dump_dir))


def merge_report(dump_dir):
    return render_merge(load_worker_dumps(dump_dir))


def report(host_path, xplane_dir=None, top_n=15):
    events = load_host_events(host_path)
    rows = per_step_rows(events)
    out = [render_host(rows)]
    if rows:
        n = len(rows)
        tot = sum(r["total_ms"] for r in rows)
        comp = sum(r["compile"] + r["trace"] for r in rows)
        out.append("steps: %d  host wall: %.2f ms  build+compile: %.2f ms "
                   "(%.1f%%)" % (n, tot, comp, comp / tot * 100 if tot
                                 else 0.0))
    if xplane_dir:
        try:
            out.append(render_device(xplane_dir, top_n))
        except Exception as e:  # xplane protos absent / empty dir
            out.append("\n(device aggregates unavailable: %s)" % e)
    return "\n".join(out)


def main(argv=None):
    os.environ.setdefault(
        "PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    p = argparse.ArgumentParser(
        description="Merged host-span + device-op perf report")
    p.add_argument("host_trace", nargs="?", default=None,
                   help="chrome-trace JSON from "
                   "observability.dump_chrome_trace / stop_profiler")
    p.add_argument("xplane_dir", nargs="?", default=None,
                   help="jax profiler trace dir with .xplane.pb dumps")
    p.add_argument("--top", type=int, default=15,
                   help="device ops to list (default 15)")
    p.add_argument("--merge", metavar="DIR", default=None,
                   help="merge a directory of per-worker JSONL telemetry "
                   "dumps (PADDLE_TPU_METRICS_SINK files) into one "
                   "cross-host report: per-step latency skew, "
                   "slowest-worker attribution, aggregate HBM watermarks")
    p.add_argument("--goodput", metavar="DIR", default=None,
                   help="merge per-worker JSONL dumps into the fleet "
                   "goodput/badput-attribution table (per-rank goodput "
                   "%%, MFU, slowest badput category, fleet goodput %%, "
                   "and the supervisor's cross-incarnation job ledger)")
    p.add_argument("--roofline", metavar="XPLANE_DIR", default=None,
                   help="per-op roofline table from a profiled trace "
                   "dir: top-k ops by device time with %% of step, "
                   "arithmetic intensity, and compute/memory/comm-bound "
                   "verdict (joins the opprof_provenance.json sidecar "
                   "stop_profiler wrote against the xplane planes)")
    p.add_argument("--gate", action="store_true",
                   help="with --roofline: exit nonzero when the top-k "
                   "table is empty or the collective lane disagrees "
                   "with the registered HLO schedule (the bench-flow "
                   "gate, like multichip_probe --predict)")
    args = p.parse_args(argv)
    if args.roofline:
        text, rc = roofline_report(args.roofline, top_n=args.top,
                                   gate=args.gate)
        print(text)
        return rc
    if args.goodput:
        print(goodput_report(args.goodput))
        return 0
    if args.merge:
        print(merge_report(args.merge))
        return 0
    if not args.host_trace:
        p.error("either HOST_TRACE, --merge DIR, --goodput DIR, or "
                "--roofline DIR is required")
    print(report(args.host_trace, args.xplane_dir, args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
