#!/usr/bin/env python
"""Merge a host-span dump with xplane device aggregates into one
per-step perf report.

The host side comes from ``observability.dump_chrome_trace(path)`` (or
the ``<profile_path>.trace.json`` stop_profiler writes): every engine
step is a "step" slice with its trace/transform/lower/compile/run
children. The device side comes from the jax profiler's xplane dump,
aggregated per op by tools/xplane_top_ops.py. Together they answer the
question the throughput number alone cannot: where did each step's wall
time go — host build (trace/transform/lower), XLA compile, dispatch, or
device kernels.

Usage:
    PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \\
        python tools/perf_report.py HOST_TRACE.json [XPLANE_DIR] [--top N]

With no XPLANE_DIR (or without the xplane protos installed) the report
is host-only.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), os.pardir)))

# The per-step breakdown columns, in pipeline order. "other" is the
# step-slice remainder not covered by any of them.
PHASES = ("trace", "transform", "lower", "compile", "run")


def load_host_events(path):
    with open(path) as f:
        trace = json.load(f)
    return [e for e in trace.get("traceEvents", [])
            if e.get("ph") == "X"]


def per_step_rows(events):
    """Group host slices into steps: each "step" slice owns every slice
    nested inside its [ts, ts+dur) window on the same pid/tid."""
    steps = sorted((e for e in events if e["name"] == "step"),
                   key=lambda e: e["ts"])
    rows = []
    for i, st in enumerate(steps):
        t0, t1 = st["ts"], st["ts"] + st.get("dur", 0.0)
        row = {"step": st.get("args", {}).get("step", i + 1),
               "total_ms": st.get("dur", 0.0) / 1e3}
        for ph in PHASES:
            row[ph] = 0.0
        for e in events:
            if e is st or e.get("pid") != st.get("pid") \
                    or e.get("tid") != st.get("tid"):
                continue
            if e["name"] in PHASES and t0 <= e["ts"] < t1:
                row[e["name"]] += e.get("dur", 0.0) / 1e3
        row["other"] = max(0.0, row["total_ms"] - sum(
            row[ph] for ph in PHASES))
        rows.append(row)
    return rows


def render_host(rows):
    lines = ["== host: per-step wall (ms) =="]
    hdr = ("step", "total") + PHASES + ("other",)
    lines.append("  ".join("%9s" % h for h in hdr))
    for r in rows:
        lines.append("  ".join(
            ["%9s" % r["step"], "%9.2f" % r["total_ms"]]
            + ["%9.2f" % r[ph] for ph in PHASES]
            + ["%9.2f" % r["other"]]))
    if not rows:
        lines.append("(no step spans in the host dump — was "
                     "PADDLE_TPU_METRICS up?)")
    return "\n".join(lines)


def render_device(xplane_dir, top_n):
    from tools.xplane_top_ops import top_ops

    rows, total = top_ops(xplane_dir, top_n=top_n)
    lines = ["", "== device: XLA-op time (total %.2f ms) ==" % total]
    for name, ms in rows:
        pct = (ms / total * 100) if total else 0.0
        lines.append("%10.3f ms  %5.1f%%  %s" % (ms, pct, name[:80]))
    return "\n".join(lines)


def report(host_path, xplane_dir=None, top_n=15):
    events = load_host_events(host_path)
    rows = per_step_rows(events)
    out = [render_host(rows)]
    if rows:
        n = len(rows)
        tot = sum(r["total_ms"] for r in rows)
        comp = sum(r["compile"] + r["trace"] for r in rows)
        out.append("steps: %d  host wall: %.2f ms  build+compile: %.2f ms "
                   "(%.1f%%)" % (n, tot, comp, comp / tot * 100 if tot
                                 else 0.0))
    if xplane_dir:
        try:
            out.append(render_device(xplane_dir, top_n))
        except Exception as e:  # xplane protos absent / empty dir
            out.append("\n(device aggregates unavailable: %s)" % e)
    return "\n".join(out)


def main(argv=None):
    os.environ.setdefault(
        "PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    p = argparse.ArgumentParser(
        description="Merged host-span + device-op perf report")
    p.add_argument("host_trace", help="chrome-trace JSON from "
                   "observability.dump_chrome_trace / stop_profiler")
    p.add_argument("xplane_dir", nargs="?", default=None,
                   help="jax profiler trace dir with .xplane.pb dumps")
    p.add_argument("--top", type=int, default=15,
                   help="device ops to list (default 15)")
    args = p.parse_args(argv)
    print(report(args.host_trace, args.xplane_dir, args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
