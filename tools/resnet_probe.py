"""ResNet-50 ceiling probe: hand-written pure-JAX train step at the bench
configuration (batch 512, bf16 activations, fp32 master weights) — the
attainable number for this formulation on this chip.

Variants:
  bare      : plain SGD, no BN running stats (round 2's probe definition)
  full      : momentum + L2 weight decay + BN running-stat updates — what
              the fluid program actually computes, the fair engine ceiling
  full-nhwc : `full` with channels-last activations (NHWC) and HWIO
              filters end-to-end — the layout question of VERDICT r3
              Next #2, answered on hardware rather than by folklore

Timing: 30 chained steps (params donated, so steps pipeline with a data
dependency) drained once — long enough that the tunnel's ~1-2s per-call
overhead is a small fraction of the window.

Usage: PYTHONPATH=/root/.axon_site:/root/repo python tools/resnet_probe.py
"""
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

B = 512
DEPTHS = [3, 4, 6, 3]
WIDTHS = [256, 512, 1024, 2048]


def conv(x, w, stride=1, pad=None, nhwc=False):
    kh = w.shape[0] if nhwc else w.shape[2]
    p = (kh - 1) // 2 if pad is None else pad
    dn = ("NHWC", "HWIO", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(p, p), (p, p)],
        dimension_numbers=jax.lax.conv_dimension_numbers(
            x.shape, w.shape, dn))


def bn_apply(x, p, running, train, nhwc=False, momentum=0.9, eps=1e-5):
    scale, bias = p
    rm, rv = running
    axes = (0, 1, 2) if nhwc else (0, 2, 3)
    sh = (1, 1, 1, -1) if nhwc else (1, -1, 1, 1)
    x32 = x.astype(jnp.float32)
    if train:
        mean = jnp.mean(x32, axes)
        var = jnp.mean(jnp.square(x32), axes) - jnp.square(mean)
        new_running = (momentum * rm + (1 - momentum) * mean,
                       momentum * rv + (1 - momentum) * var)
    else:
        mean, var = rm, rv
        new_running = running
    y = (x32 - mean.reshape(sh)) * jax.lax.rsqrt(var.reshape(sh) + eps)
    y = y * scale.reshape(sh) + bias.reshape(sh)
    return y.astype(x.dtype), new_running


def init(rng, nhwc=False):
    params, bns = {}, {}

    def w(name, o, i, k):
        arr = rng.randn(o, i, k, k) * np.sqrt(2.0 / (i * k * k))
        if nhwc:
            arr = arr.transpose(2, 3, 1, 0)          # OIHW -> HWIO
        params[name] = jnp.asarray(arr, jnp.float32)

    def bn(name, c):
        params[name + "_bn"] = (jnp.ones((c,)), jnp.zeros((c,)))
        bns[name + "_bn"] = (jnp.zeros((c,)), jnp.ones((c,)))

    w("stem", 64, 3, 7); bn("stem", 64)
    cin = 64
    for si, (n, width) in enumerate(zip(DEPTHS, WIDTHS)):
        mid = width // 4
        for bi in range(n):
            pre = "s%db%d" % (si, bi)
            w(pre + "_1", mid, cin, 1); bn(pre + "_1", mid)
            w(pre + "_2", mid, mid, 3); bn(pre + "_2", mid)
            w(pre + "_3", width, mid, 1); bn(pre + "_3", width)
            if cin != width:
                w(pre + "_sc", width, cin, 1); bn(pre + "_sc", width)
            cin = width
    params["fc"] = jnp.asarray(rng.randn(2048, 1000) * 0.01, jnp.float32)
    params["fcb"] = jnp.zeros((1000,))
    return params, bns


def forward(params, bns, x, labels, train, nhwc=False):
    new_bns = {}

    def apply_bn(name, h):
        y, nr = bn_apply(h, params[name + "_bn"], bns[name + "_bn"], train,
                         nhwc)
        new_bns[name + "_bn"] = nr
        return y

    bf = lambda a: a.astype(jnp.bfloat16)
    h = bf(x)
    h = apply_bn("stem", conv(h, bf(params["stem"]), 2, nhwc=nhwc))
    h = jax.nn.relu(h)
    window = (1, 3, 3, 1) if nhwc else (1, 1, 3, 3)
    strides = (1, 2, 2, 1) if nhwc else (1, 1, 2, 2)
    pads = (((0, 0), (1, 1), (1, 1), (0, 0)) if nhwc
            else ((0, 0), (0, 0), (1, 1), (1, 1)))
    h = jax.lax.reduce_window(h, -jnp.inf, jax.lax.max, window, strides,
                              pads)
    cin = 64
    for si, (n, width) in enumerate(zip(DEPTHS, WIDTHS)):
        mid = width // 4
        for bi in range(n):
            pre = "s%db%d" % (si, bi)
            stride = 2 if (bi == 0 and si > 0) else 1
            idn = h
            y = jax.nn.relu(apply_bn(
                pre + "_1", conv(h, bf(params[pre + "_1"]), 1, nhwc=nhwc)))
            y = jax.nn.relu(apply_bn(
                pre + "_2", conv(y, bf(params[pre + "_2"]), stride,
                                 nhwc=nhwc)))
            y = apply_bn(pre + "_3", conv(y, bf(params[pre + "_3"]), 1,
                                          nhwc=nhwc))
            if cin != width:
                idn = apply_bn(
                    pre + "_sc", conv(h, bf(params[pre + "_sc"]), stride,
                                      nhwc=nhwc))
            h = jax.nn.relu(y + idn)
            cin = width
    h = jnp.mean(h.astype(jnp.float32), (1, 2) if nhwc else (2, 3))
    logits = h @ params["fc"] + params["fcb"]
    lse = jax.nn.logsumexp(logits, -1)
    ll = jnp.take_along_axis(logits, labels[:, None], -1)[:, 0]
    return jnp.mean(lse - ll), new_bns


@partial(jax.jit, static_argnames=("mode", "nhwc"),
         donate_argnums=(0, 1, 2))
def step(params, bns, vel, x, labels, mode="full", nhwc=False):
    (loss, new_bns), grads = jax.value_and_grad(
        forward, has_aux=True)(params, bns, x, labels, True, nhwc)
    lr = 0.1
    if mode == "bare":
        params = jax.tree.map(lambda w, g: w - lr * g, params, grads)
        return params, bns, vel, loss
    mom, wd = 0.9, 1e-4
    vel = jax.tree.map(lambda v, g, w: mom * v + g + wd * w,
                       vel, grads, params)
    params = jax.tree.map(lambda w, v: w - lr * v, params, vel)
    return params, new_bns, vel, loss


def run(mode, steps=30, warmup=3):
    nhwc = mode.endswith("-nhwc")
    base = mode.split("-")[0]
    rng = np.random.RandomState(0)
    params, bns = init(rng, nhwc)
    vel = jax.tree.map(jnp.zeros_like, params)
    shape = (B, 224, 224, 3) if nhwc else (B, 3, 224, 224)
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 1000, (B,)), jnp.int32)
    for _ in range(warmup):
        params, bns, vel, loss = step(params, bns, vel, x, labels,
                                      mode=base, nhwc=nhwc)
    jax.device_get(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, bns, vel, loss = step(params, bns, vel, x, labels,
                                      mode=base, nhwc=nhwc)
    jax.device_get(loss)
    return B * steps / (time.perf_counter() - t0)


if __name__ == "__main__":
    import sys

    modes = sys.argv[1:] or ["full", "full-nhwc", "full", "full-nhwc"]
    print("backend:", jax.default_backend())
    for mode in modes:
        print("%s probe: %.1f img/s" % (mode, run(mode)), flush=True)
