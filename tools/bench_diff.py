#!/usr/bin/env python
"""Regression differ for two bench rounds (``BENCH_*.json``).

The bench trajectory has had no automated comparison since r05 — this
closes that: point it at any two rounds and it diffs every numeric leaf
(the flat throughput metrics AND the nested ``counters`` blocks bench.py
emits — compile walls, cache hit/miss, pipeline/serving/health/elastic/
sentinel/goodput sub-dicts), classifies each delta by the metric's
direction, and exits nonzero when a directional metric regressed past
the threshold:

    python tools/bench_diff.py BENCH_r05.json BENCH_r06.json
    python tools/bench_diff.py --threshold 0.10 old.json new.json
    python tools/bench_diff.py --all old.json new.json   # every delta

Direction is inferred from the key name: throughput-like suffixes
(``*_per_sec``, ``*speedup*``, ``*qps*``, ``*hit*``, ``*goodput*``,
``*frac``, ``*mfu*``) are higher-better; cost-like ones (``*_ms``,
``*_bytes``, ``*miss*``, ``*evict*``, ``*trips*``, ``*crashes*``,
``*_wall*``, ``*transpose*``) are lower-better; anything else is
informational (printed under --all, never a failure). Both file shapes are accepted: the raw
``bench.py`` stdout JSON and the archived ``{"cmd", "rc", "parsed"}``
wrapper the rounds are stored as.
"""
import argparse
import json
import sys

HIGHER = ("per_sec", "per_s", "speedup", "qps", "hit", "goodput",
          "frac", "mfu", "fill", "efficiency", "max_batch",
          "savings_bytes")
LOWER = ("_ms", "_bytes", "_ns", "miss", "evict", "trips", "crashes",
         "wall", "dropped", "failed", "skew", "spread", "overhead",
         "badput", "retries", "transpose", "unattributed", "rejected",
         "shed_", "expired")


def direction(key):
    """-> 'higher' | 'lower' | None (informational)."""
    k = key.lower()
    # the most specific (longest) matching cue wins, so e.g.
    # "cache_miss_ms" reads as lower-better via _ms AND miss — agreeing
    # — while "prefetch_hit" is higher-better despite no suffix match
    hi = max((len(c) for c in HIGHER if c in k), default=0)
    lo = max((len(c) for c in LOWER if c in k), default=0)
    if hi == lo:
        return None
    return "higher" if hi > lo else "lower"


def numeric_leaves(obj, prefix=""):
    """Flatten every numeric leaf: {'counters.goodput.frac': 0.99, ...}
    (bools excluded — rc/ok flags are not metrics)."""
    out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(numeric_leaves(v, prefix + str(k) + "."))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix[:-1]] = float(obj)
    return out


def load_round(path):
    """Accept both the archived wrapper ({"parsed": {...}}) and the raw
    bench.py output; returns the metric dict to diff."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    return doc


def diff_rounds(old, new, threshold):
    """-> (rows, regressions). A row is (key, old, new, delta_frac,
    direction, verdict) sorted worst-first; regressions counts rows
    whose directional delta exceeds ``threshold``."""
    a, b = numeric_leaves(old), numeric_leaves(new)
    rows, regressions = [], 0
    for key in sorted(set(a) | set(b)):
        va, vb = a.get(key), b.get(key)
        if va is None or vb is None:
            rows.append((key, va, vb, None, direction(key), "only-one"))
            continue
        if va == vb:
            continue
        delta = (vb - va) / abs(va) if va else float("inf")
        d = direction(key)
        verdict = "info"
        if d is not None:
            worse = delta < -threshold if d == "higher" \
                else delta > threshold
            better = delta > threshold if d == "higher" \
                else delta < -threshold
            verdict = ("REGRESSED" if worse
                       else "improved" if better else "ok")
            if worse:
                regressions += 1
        rows.append((key, va, vb, delta, d, verdict))
    order = {"REGRESSED": 0, "improved": 1, "ok": 2, "info": 3,
             "only-one": 4}
    rows.sort(key=lambda r: (order[r[5]],
                             -abs(r[3]) if r[3] is not None else 0.0))
    return rows, regressions


def _fmt(v):
    if v is None:
        return "-"
    return "%.6g" % v


def main(argv=None):
    p = argparse.ArgumentParser(
        description="diff the numeric metrics + counters blocks of two "
        "BENCH_*.json rounds; exit 1 when a directional metric "
        "regressed past the threshold")
    p.add_argument("old", help="baseline round JSON")
    p.add_argument("new", help="candidate round JSON")
    p.add_argument("--threshold", type=float, default=0.25,
                   help="relative regression tolerance (default 0.25 — "
                   "CPU-probe walls are noisy; tighten for real "
                   "hardware rounds)")
    p.add_argument("--all", action="store_true",
                   help="also print unchanged-direction/informational "
                   "deltas and metrics present in only one round")
    args = p.parse_args(argv)
    rows, regressions = diff_rounds(load_round(args.old),
                                    load_round(args.new),
                                    args.threshold)
    shown = 0
    print("%-52s %12s %12s %9s  %s"
          % ("metric", "old", "new", "delta", "verdict"))
    for key, va, vb, delta, d, verdict in rows:
        if not args.all and verdict in ("info", "only-one", "ok"):
            continue
        shown += 1
        print("%-52s %12s %12s %9s  %s"
              % (key[:52], _fmt(va), _fmt(vb),
                 ("%+.1f%%" % (100.0 * delta)) if delta is not None
                 else "-",
                 verdict + ("" if d is None else " (%s-better)" % d)))
    if not shown:
        print("(no directional deltas beyond %.0f%% — pass --all for "
              "the full diff)" % (100.0 * args.threshold))
    print("\nbench_diff: %d regression(s) past %.0f%% against %s"
          % (regressions, 100.0 * args.threshold, args.old))
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
