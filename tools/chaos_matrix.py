"""Chaos matrix: every chaos acceptance gate in one command.

Runs each gate script (``tools/chaos_run.py`` for the training gang,
``tools/serve_probe.py`` for the serving fleet) as its own subprocess
(distinct rendezvous ports, distinct workdirs), parses the one-line
JSON verdict each gate prints, and renders a pass/fail table. Exit
code 0 iff every gate passed — this is the single entry point CI (or a
reviewer) runs to prove the whole failure-domain story at once:

    gate      injected fault                   proven recovery path
    -------   ------------------------------   -------------------------
    base      worker kill + step NaN           respawn + rollback/replay
    hang      wedged worker (no heartbeat)     watchdog detect + restart
    shrink    permanent rank loss mid-window   gang shrink, survivors
              (async dispatch depth 4)         finish with parity
    quorum    dead checkpoint disk + kill      restore from peer replica
    sdc       silent bitflips (transient +     digest detect, replay
              persistent)                      vote, blame, quarantine
    preempt   SIGTERM eviction                 drain + checkpoint + free
                                               restart (no budget spent)
    layout    rank loss mid-window with the    shrink + replay stays
              NHWC layout pass rewriting the   bit-exact with HWIO-baked
              conv probe (PADDLE_TPU_LAYOUT)   weights in the checkpoints
    zero1     permanent rank loss with the     mesh shrink reshards the
              ZeRO-1 sharded Momentum update   partitioned velocity
              on the dp mesh (PADDLE_TPU_ZERO) slots; survivors keep
                                               fault-free parity
    overload  4x sustained serving overload    admission control sheds;
                                               queue stays bounded,
                                               every future resolves,
                                               admitted p99 holds SLO
    hedge     serving-fleet worker killed      hedged retry answers via
              mid-flight (+ a 0.5s straggler)  the survivor under ONE
                                               stitched trace; breaker
                                               trips, half-open recovers

Usage::

    python tools/chaos_matrix.py                  # all gates (~minutes)
    python tools/chaos_matrix.py --only sdc,hang  # a subset
    python tools/chaos_matrix.py --steps 20       # shorter runs

Every gate asserts bit-exact (or, under --mesh paths, allclose) loss
parity against a fault-free reference on top of its own recovery-path
assertions — see chaos_run.py for what each flag checks. Every gate
also asserts the supervisor's goodput job ledger conserves (categories
sum to wall within 1%) and charged the injected fault's wall cost to
the right badput category (kill -> restart_downtime, preempt ->
preempt_drain, shrink -> shrink_rejit); the table's ``badput=`` detail
shows the attribution.
"""

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
CHAOS_RUN = os.path.join(HERE, "chaos_run.py")
SERVE_PROBE = os.path.join(HERE, "serve_probe.py")

# (name, gate script, extra argv). Ports are assigned below, spaced so
# a lingering listener from one gate can never collide with the next;
# serve_probe gates are in-process (no rendezvous) and get only --seed.
GATES = [
    ("base", CHAOS_RUN, []),
    ("hang", CHAOS_RUN, ["--hang"]),
    # depth 4: the permanent loss lands MID async dispatch window, so
    # the in-flight deferred steps must retire/invalidate cleanly
    # before the survivors replay (the gang-level half of the live
    # shrink coverage; tests/test_elastic.py has the in-process half)
    ("shrink", CHAOS_RUN, ["--shrink", "--dispatch-steps", "4"]),
    ("quorum", CHAOS_RUN, ["--ckpt-replicas", "1", "--spec",
                           "disk_fail@rank0:step12;"
                           "worker_kill@rank0:step14"]),
    ("sdc", CHAOS_RUN, ["--sdc"]),
    ("preempt", CHAOS_RUN, ["--preempt"]),
    # conv probe + whole-program NHWC rewrite (analysis/layout.py): the
    # baked-HWIO filter rides the checkpoints through a permanent rank
    # loss mid dispatch window — the layout pass may not perturb
    # bit-exact replay under any recovery path
    ("layout", CHAOS_RUN, ["--layout", "--shrink",
                           "--dispatch-steps", "4"]),
    # the ZeRO-1 sharded weight update on the dp mesh: the permanent
    # rank loss shrinks the workers' mesh while the Momentum velocity
    # slots live dp-sharded — the reshard-on-shrink seam must migrate
    # the partitioned optimizer state and keep fault-free parity
    # (tests/test_elastic.py has the in-process half of this coverage)
    ("zero1", CHAOS_RUN, ["--shrink", "--mesh", "--zero1"]),
    # the serving-side failure domain (paddle_tpu/inference/admission):
    # sustained 4x overload against the armed admission stack — queue
    # bounded, served/rejected/expired conserve exactly, admitted p99
    # holds the SLO
    ("overload", SERVE_PROBE, ["--overload", "--duration", "2"]),
    # worker killed mid-flight behind the FleetRouter: hedged retries
    # answer correctly via the survivor under one stitched trace, the
    # sick worker's breaker trips, and a half-open probe recovers it
    ("hedge", CHAOS_RUN, ["--serve-retry"]),
]


def run_gate(name, script, extra, args, port):
    if script == SERVE_PROBE:
        cmd = [sys.executable, script, "--seed", str(args.seed)] + extra
    else:
        cmd = [sys.executable, script, "--steps", str(args.steps),
               "--nproc", str(args.nproc), "--seed", str(args.seed),
               "--started_port", str(port)] + extra
    t0 = time.monotonic()
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=args.timeout)
        rc, out = proc.returncode, proc.stdout
        tail = proc.stderr.strip().splitlines()[-1:] if rc else []
    except subprocess.TimeoutExpired:
        rc, out, tail = -1, "", ["timeout after %ds" % args.timeout]
    wall = time.monotonic() - t0
    # the verdict is the LAST stdout line that parses as a JSON object
    verdict = None
    for line in reversed(out.strip().splitlines()):
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if isinstance(cand, dict) and "ok" in cand:
            verdict = cand
            break
    ok = rc == 0 and verdict is not None and verdict.get("ok") is True
    return {"gate": name, "ok": ok, "rc": rc, "wall_s": round(wall, 1),
            "verdict": verdict, "note": "; ".join(tail)}


def main():
    parser = argparse.ArgumentParser("chaos_matrix")
    parser.add_argument("--only", default=None,
                        help="comma-separated gate names to run "
                             "(default: all of %s)"
                        % ",".join(n for n, _, _ in GATES))
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--nproc", type=int, default=2)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--timeout", type=int, default=600,
                        help="per-gate wall-clock budget in seconds")
    parser.add_argument("--started_port", type=int, default=6400,
                        help="first rendezvous port; each gate gets its "
                             "own +16 block")
    args = parser.parse_args()

    want = None
    if args.only:
        want = {n.strip() for n in args.only.split(",") if n.strip()}
        unknown = want - {n for n, _, _ in GATES}
        if unknown:
            parser.error("unknown gate(s): %s" % ", ".join(sorted(unknown)))

    rows = []
    for i, (name, script, extra) in enumerate(GATES):
        if want is not None and name not in want:
            continue
        port = args.started_port + 16 * i
        print("chaos_matrix: running %-8s ..." % name, flush=True)
        rows.append(run_gate(name, script, extra, args, port))
        row = rows[-1]
        print("chaos_matrix: %-8s %s in %.1fs"
              % (name, "PASS" if row["ok"] else "FAIL", row["wall_s"]),
              flush=True)

    width = max(len(r["gate"]) for r in rows) if rows else 4
    print()
    print("%-*s  %-4s  %6s  %s" % (width, "gate", "ok", "wall", "detail"))
    print("%s  %s  %s  %s" % ("-" * width, "-" * 4, "-" * 6, "-" * 40))
    for r in rows:
        v = r["verdict"] or {}
        if r["ok"]:
            if v.get("fleet"):          # the serving hedge/retry gate
                f = v["fleet"]
                detail = ("retries=%s hedge_wins=%s trips=%s stitched=%s"
                          % (f.get("retries"), f.get("hedge_wins"),
                             f.get("breaker_trips"),
                             (v.get("traces") or {}).get("stitched")))
            elif v.get("overload"):     # the serving overload gate
                o = v["overload"]
                turned = (sum((o.get("rejected") or {}).values())
                          + o.get("shed_evicted", 0)
                          + o.get("expired", 0))
                detail = ("served=%s turned_away=%s depth_max=%s "
                          "p99=%sms" % (o.get("served"), turned,
                                        o.get("depth_max"),
                                        o.get("served_p99_ms")))
            else:
                detail = ",".join(v.get("sentinel_events")
                                  or v.get("recovery_events") or [])[:60]
            if v.get("goodput_attr"):
                # where the injected fault's wall cost landed (asserted
                # per-gate in chaos_run.py — this column is the summary)
                detail += "  badput=%s" % v["goodput_attr"]
        else:
            detail = "; ".join(v.get("problems", [])) or r["note"] \
                or "rc %s, no verdict" % r["rc"]
        print("%-*s  %-4s  %5.1fs  %s"
              % (width, r["gate"], "PASS" if r["ok"] else "FAIL",
                 r["wall_s"], detail[:100]))
    n_fail = sum(1 for r in rows if not r["ok"])
    print("\nchaos_matrix: %d/%d gates passed"
          % (len(rows) - n_fail, len(rows)))
    return 1 if n_fail else 0


if __name__ == "__main__":
    sys.exit(main())
