"""Real-chip smoke test for the Pallas flash kernels: lowering + numerics.
Run under the driver env (JAX_PLATFORMS=axon). Prints one status line per
config; exits nonzero on any lowering failure."""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.kernels.flash_attention import (
    flash_attention, _xla_attention)

print("backend:", jax.default_backend(), jax.devices())
assert jax.default_backend() == "tpu", "not on TPU"

failures = []


def check(name, causal, lens, rate, B=2, H=4, T=512, D=64, dtype=jnp.float32):
    q = jnp.asarray(np.random.RandomState(0).randn(B, H, T, D), dtype)
    k = jnp.asarray(np.random.RandomState(1).randn(B, H, T, D), dtype)
    v = jnp.asarray(np.random.RandomState(2).randn(B, H, T, D), dtype)
    sl = jnp.asarray(lens, jnp.int32) if lens is not None else None

    def loss(q_, k_, v_):
        return jnp.sum(flash_attention(
            q_, k_, v_, sl, 7, causal, None, rate, 128, 128, False
        ).astype(jnp.float32) ** 2)

    try:
        t0 = time.time()
        f = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
        val, grads = f(q, k, v)
        jax.block_until_ready(grads)
        t1 = time.time()
        if rate == 0.0:
            ref_val, ref_grads = jax.jit(jax.value_and_grad(
                lambda a, b, c: jnp.sum(_xla_attention(
                    a, b, c, causal, D ** -0.5, sl
                ).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2)))(q, k, v)
            for g, rg, nm in zip(grads, ref_grads, ("dq", "dk", "dv")):
                err = float(jnp.max(jnp.abs(
                    g.astype(jnp.float32) - rg.astype(jnp.float32))))
                scale_ref = float(jnp.max(jnp.abs(rg.astype(jnp.float32))))
                assert err < max(5e-2 if dtype == jnp.bfloat16 else 1e-2,
                                 2e-2 * scale_ref), (nm, err, scale_ref)
        else:
            assert np.isfinite(float(val))
            for g in grads:
                assert bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))
        print("OK  %-28s compile+run %.1fs" % (name, t1 - t0))
    except Exception as e:
        failures.append(name)
        print("FAIL %-28s %s" % (name, str(e)[:400]))


check("plain_f32", False, None, 0.0)
check("causal_f32", True, None, 0.0)
check("seqlens_f32", False, [512, 300], 0.0)
check("causal_seqlens_bf16", True, [512, 300], 0.0, dtype=jnp.bfloat16)
check("dropout_bf16", True, [512, 300], 0.1, dtype=jnp.bfloat16)

if failures:
    print("FAILURES:", failures)
    sys.exit(1)
print("all flash configs lower and run on TPU")
