"""BERT-base ceiling probe: a hand-written pure-JAX train step at the bench
configuration (batch 64, seq 128, bf16 activations, fp32 master weights,
Adam, MLM + NSP heads, dropout off) — the practical attainable number for
this model formulation on this chip, the BERT analog of round 2's ResNet
probe (MFU.md). Run under the driver env / axon site path.

Usage: PYTHONPATH=/root/.axon_site:/root/repo python tools/bert_probe.py
"""
import time

import numpy as np
import jax
import jax.numpy as jnp

V, MAXP = 30522, 512
D, L, H, FF = 768, 12, 12, 3072
B, T = 64, 128
DH = D // H


def init_params(rng):
    p = {}

    def nrm(key, *shape):
        return jnp.asarray(rng.randn(*shape) * 0.02, jnp.float32)

    p["wemb"] = nrm("wemb", V, D)
    p["pemb"] = nrm("pemb", MAXP, D)
    p["semb"] = nrm("semb", 2, D)
    p["emb_ln"] = (jnp.ones((D,)), jnp.zeros((D,)))
    for i in range(L):
        lp = {}
        for n in ("q", "k", "v", "o"):
            lp[n] = nrm(n, D, D)
        lp["ff1"], lp["ff1b"] = nrm("f1", D, FF), jnp.zeros((FF,))
        lp["ff2"], lp["ff2b"] = nrm("f2", FF, D), jnp.zeros((D,))
        lp["ln1"] = (jnp.ones((D,)), jnp.zeros((D,)))
        lp["ln2"] = (jnp.ones((D,)), jnp.zeros((D,)))
        p["layer%d" % i] = lp
    p["mlm_w"], p["mlm_b"] = nrm("mw", D, D), jnp.zeros((D,))
    p["mlm_ln"] = (jnp.ones((D,)), jnp.zeros((D,)))
    p["mlm_out"], p["mlm_ob"] = nrm("mo", D, V), jnp.zeros((V,))
    p["pool_w"], p["pool_b"] = nrm("pw", D, D), jnp.zeros((D,))
    p["nsp_w"], p["nsp_b"] = nrm("nw", D, 2), jnp.zeros((2,))
    return p


def ln(x, gb):
    g, b = gb
    x32 = x.astype(jnp.float32)
    m = jnp.mean(x32, -1, keepdims=True)
    v = jnp.mean(jnp.square(x32 - m), -1, keepdims=True)
    return ((x32 - m) * jax.lax.rsqrt(v + 1e-5) * g + b).astype(x.dtype)


def bf(x):
    return x.astype(jnp.bfloat16)


def forward(p, batch):
    ids, pos, sent, mlab, mw, nslab = batch
    x = (p["wemb"][ids] + p["pemb"][pos] + p["semb"][sent])
    x = bf(ln(x, p["emb_ln"]))
    for i in range(L):
        lp = p["layer%d" % i]
        q = (x @ bf(lp["q"])).reshape(B, T, H, DH).transpose(0, 2, 1, 3)
        k = (x @ bf(lp["k"])).reshape(B, T, H, DH).transpose(0, 2, 1, 3)
        v = (x @ bf(lp["v"])).reshape(B, T, H, DH).transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (DH ** -0.5)
        w = jax.nn.softmax(s.astype(jnp.float32), -1).astype(x.dtype)
        c = jnp.einsum("bhqk,bhkd->bhqd", w, v).transpose(0, 2, 1, 3)
        c = c.reshape(B, T, D) @ bf(lp["o"])
        x = bf(ln(x + c, lp["ln1"]))
        f = jax.nn.gelu(x @ bf(lp["ff1"]) + bf(lp["ff1b"]))
        f = f @ bf(lp["ff2"]) + bf(lp["ff2b"])
        x = bf(ln(x + f, lp["ln2"]))
    mh = ln(jax.nn.gelu(x @ bf(p["mlm_w"]) + bf(p["mlm_b"])), p["mlm_ln"])
    logits = (mh @ bf(p["mlm_out"]) + bf(p["mlm_ob"])).astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, -1)
    ll = jnp.take_along_axis(logits, mlab[..., None], -1)[..., 0]
    mlm = jnp.sum((lse - ll) * mw) / (jnp.sum(mw) + 1e-6)
    pooled = jnp.tanh(x[:, 0].astype(jnp.float32) @ p["pool_w"]
                      + p["pool_b"])
    nl = pooled @ p["nsp_w"] + p["nsp_b"]
    nsp = jnp.mean(jax.nn.logsumexp(nl, -1)
                   - jnp.take_along_axis(nl, nslab[:, None], -1)[:, 0])
    return mlm + nsp


def adam_update(p, g, m, v, t, lr=1e-4, b1=0.9, b2=0.999, eps=1e-8):
    m = jax.tree.map(lambda a, b: b1 * a + (1 - b1) * b, m, g)
    v = jax.tree.map(lambda a, b: b2 * a + (1 - b2) * jnp.square(b), v, g)
    bc1, bc2 = 1 - b1 ** t, 1 - b2 ** t
    p = jax.tree.map(
        lambda w, mm, vv: w - lr * (mm / bc1) / (jnp.sqrt(vv / bc2) + eps),
        p, m, v)
    return p, m, v


@jax.jit
def step(p, m, v, t, batch):
    loss, g = jax.value_and_grad(forward)(p, batch)
    p, m, v = adam_update(p, g, m, v, t)
    return p, m, v, t + 1, loss


def main():
    print("backend:", jax.default_backend())
    rng = np.random.RandomState(0)
    p = init_params(rng)
    m = jax.tree.map(jnp.zeros_like, p)
    v = jax.tree.map(jnp.zeros_like, p)
    t = jnp.float32(1)
    batch = (
        jnp.asarray(rng.randint(0, V, (B, T)), jnp.int32),
        jnp.asarray(np.tile(np.arange(T), (B, 1)), jnp.int32),
        jnp.zeros((B, T), jnp.int32),
        jnp.asarray(rng.randint(0, V, (B, T)), jnp.int32),
        jnp.asarray(rng.rand(B, T) < 0.15, jnp.float32),
        jnp.asarray(rng.randint(0, 2, (B,)), jnp.int32),
    )
    for _ in range(3):
        p, m, v, t, loss = step(p, m, v, t, batch)
    jax.device_get(loss)
    steps = 10
    t0 = time.perf_counter()
    for _ in range(steps):
        p, m, v, t, loss = step(p, m, v, t, batch)
    jax.device_get(loss)
    dt = time.perf_counter() - t0
    sps = B * steps / dt
    gflop = 6 * 110e6 * T / 1e9  # ~6*params*tokens fwd+bwd
    print("probe: %.1f samples/s  (~%.1f TFLOP/s, %.1f%% of 197 bf16 peak)"
          % (sps, sps * gflop / 1e3, sps * gflop / 1e3 / 197 * 100))


if __name__ == "__main__":
    main()
