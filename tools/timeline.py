#!/usr/bin/env python
"""Chrome-trace timeline export (capability parity with the REFERENCE
repo's tools/timeline.py:36, which converts its profiler protos into a
chrome://tracing JSON; here the source is the jax profiler's xplane
dump, so the same workflow holds: profile with paddle_tpu.profiler,
convert, open in chrome://tracing or https://ui.perfetto.dev).

Usage: PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
           python tools/timeline.py <trace_dir> <out.json> [line_filter]

Every xplane plane becomes a chrome "process" and every line a "thread";
events map to complete ("ph": "X") slices with microsecond timestamps.
``line_filter`` (substring, e.g. "XLA Ops") keeps only matching lines.
"""
import json
import os
import sys

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), os.pardir)))


def xplane_to_chrome_trace(trace_dir, line_filter=None):
    """-> chrome-trace dict {"traceEvents": [...], "displayTimeUnit": "ms"}
    from every distinct .xplane.pb under ``trace_dir`` (byte-identical
    duplicate dumps are skipped by the shared plane iterator)."""
    from tools.xplane_top_ops import iter_planes

    events = []
    for pid, plane in enumerate(iter_planes(trace_dir), start=1):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": plane.name}})
        meta = {m.id: m.name for m in plane.event_metadata.values()}
        for tid, line in enumerate(plane.lines):
            if line_filter and line_filter not in line.name:
                continue
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": tid,
                           "args": {"name": line.name}})
            t0_ns = line.timestamp_ns
            for e in line.events:
                events.append({
                    "name": meta.get(e.metadata_id, "?"),
                    "ph": "X",
                    "pid": pid,
                    "tid": tid,
                    "ts": (t0_ns + e.offset_ps / 1e3) / 1e3,  # us
                    "dur": e.duration_ps / 1e6,               # us
                })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main():
    trace_dir, out = sys.argv[1], sys.argv[2]
    line_filter = sys.argv[3] if len(sys.argv) > 3 else None
    trace = xplane_to_chrome_trace(trace_dir, line_filter)
    with open(out, "w") as f:
        json.dump(trace, f)
    print("wrote %d events to %s" % (len(trace["traceEvents"]), out))


if __name__ == "__main__":
    main()
