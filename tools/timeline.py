#!/usr/bin/env python
"""Chrome-trace timeline CLI — thin shim over the package converter.

The xplane→chrome-trace conversion now lives at
``paddle_tpu.observability.tracing.xplane_to_chrome_trace`` so the
package owns ONE trace-export entry point
(``observability.dump_chrome_trace(path, xplane_dir=...)`` merges host
spans + device planes into a single perfetto view). This CLI is kept
for the reference workflow (reference repo's tools/timeline.py:36 —
convert a profiler dump, open in chrome://tracing):

Usage: PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
           python tools/timeline.py <trace_dir> <out.json> [line_filter]
"""
import json
import os
import sys

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), os.pardir)))

from paddle_tpu.observability.tracing import (  # noqa: E402,F401
    xplane_to_chrome_trace,
)


def main():
    trace_dir, out = sys.argv[1], sys.argv[2]
    line_filter = sys.argv[3] if len(sys.argv) > 3 else None
    trace = xplane_to_chrome_trace(trace_dir, line_filter)
    with open(out, "w") as f:
        json.dump(trace, f)
    print("wrote %d events to %s" % (len(trace["traceEvents"]), out))


if __name__ == "__main__":
    main()
