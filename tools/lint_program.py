#!/usr/bin/env python
"""Lint a Program with the paddle_tpu.analysis verifier.

Two modes:

  * ``--program FILE`` — lint a serialized program (the native
    ``ProgramDescData.serialize_to_string`` bytes, a pickle of those
    bytes, or a pickled Program).
  * ``--model NAME`` (repeatable; default: every book model plus
    mnist_mlp) — build the named ``tests/book`` model, append an Adam
    training pass so the backward/optimizer segments are linted too, and
    verify main + startup programs with the real feed/fetch lists.

All six checkers run (use-before-def, shape-dtype, waw-hazard,
grad-pairing, dead-op, sharding). ``--opt-level N`` first runs the
transform pipeline (analysis/transforms.py) over each program and lints
the *transformed* desc — the same desc the engine would compile at that
level. ``--memory`` additionally prints each main program's memory plan
(analysis/memory.py): liveness peak + top-10 contributors, the
donate/held split, and the remat segment choice under ``--budget-mb``
(default: the device-derived HBM budget, usually absent on CPU — remat
reads "off"). ``--freeze`` additionally runs each built model through
the inference freeze + INT8 post-training-quantization pipeline
(paddle_tpu.inference) and prints the op/var counts before/after, the
batch-norm folds, and the quantized-vs-skipped table with per-op
calibrated ranges. ``--layout`` additionally prints each program's NHWC
layout-assignment plan (analysis/layout.py, dry run): the ops assigned
NHWC, every transpose2 seam and where it lands, and the weights that
would be re-laid-out OIHW->HWIO. ``--spmd`` additionally prints each
program's static SPMD report (analysis/spmd.py) under the --mesh/--rule
table: sharding table, predicted collective schedule with bytes,
per-device peak vs replicated peak, and the replicated-optimizer-state
(ZeRO-1) ledger; add ``--zero1`` to analyze with the sharded weight
update ON — the schedule gains the per-param all-gathers and the
ledger reads post-sharding (near zero when the plan covers the
optimizer state). ``--flags`` cross-references the README flags table
against the flags.py DEFS registry and exits 1 on missing/stale rows.
``--provenance`` lints the opprof lowering provenance: every registered
op type's ``pt.<type>.<block>_<idx>`` scope tag round-trips through
``parse_tag``, a real mnist_mlp training compile covers every live op
with a provenance entry + registry cost row and at least one tag lands
in the compiled HLO op_metadata, and no paddle_tpu module imports from
tools/ (library -> CLI layering). Exit code 1 iff any ERROR finding.

  python tools/lint_program.py --model mnist_mlp --spmd --mesh dp=2
  python tools/lint_program.py --model mnist_mlp --spmd --zero1
  python tools/lint_program.py --flags

  python tools/lint_program.py
  python tools/lint_program.py --list-passes
  python tools/lint_program.py --model fit_a_line --model word2vec -v
  python tools/lint_program.py --mesh dp=4,tp=2 --rule '.*fc.*w:,tp'
  python tools/lint_program.py --program /tmp/main.prog --opt-level 2
  python tools/lint_program.py --model mnist_mlp --memory --budget-mb 4
  python tools/lint_program.py --model recognize_digits_conv --freeze
"""

import argparse
import importlib.util
import os
import pickle
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# Lint on the host CPU backend; never grabs TPU devices.
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _load_book_builders():
    """Import tests/book/test_book_models.py by path (tests/ is not a
    package) and return its BOOK_BUILDERS registry plus the mnist MLP."""
    builders = {}
    spec = importlib.util.spec_from_file_location(
        "_book_models",
        os.path.join(REPO_ROOT, "tests", "book", "test_book_models.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    builders.update(mod.BOOK_BUILDERS)

    spec = importlib.util.spec_from_file_location(
        "_mnist_mlp", os.path.join(REPO_ROOT, "tests", "test_mnist_mlp.py"))
    mlp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mlp)

    def mnist_mlp():
        img, label, avg_loss, acc = mlp.build_mlp()
        return ["img", "label"], acc, avg_loss

    builders["mnist_mlp"] = mnist_mlp
    return builders


def _parse_mesh_axes(spec):
    """'dp=4,tp=2' -> {'dp': 4, 'tp': 2} (static; no devices)."""
    if not spec:
        return None
    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        axes[name.strip()] = int(size)
    return axes


def _parse_mesh(spec):
    """'dp=4,tp=2' -> Mesh (over however many host devices exist)."""
    axes = _parse_mesh_axes(spec)
    if axes is None:
        return None
    from paddle_tpu.parallel.mesh import make_mesh

    return make_mesh(axes)


def _parse_rules(rule_args):
    """['pat:axis0,axis1', ...] -> ShardingRules; empty axis slots ('')
    mean an unsharded dim."""
    if not rule_args:
        return None
    from jax.sharding import PartitionSpec
    from paddle_tpu.parallel.sharding import ShardingRules

    rules = ShardingRules()
    for raw in rule_args:
        pat, _, spec = raw.rpartition(":")
        if not pat:
            raise SystemExit("bad --rule %r (want PATTERN:axis0,axis1)" % raw)
        entries = [a.strip() or None for a in spec.split(",")]
        rules.add(pat, PartitionSpec(*entries))
    return rules


def _list_passes():
    """Every registered pass: name, kind (checker/transform), and whether
    it runs by default — checkers iff in DEFAULT_PASSES, transforms iff
    enabled at the opt_level flag's default value."""
    from paddle_tpu import flags
    from paddle_tpu.analysis.passes import DEFAULT_PASSES, PASS_REGISTRY

    default_level = flags.DEFS["opt_level"][1]
    print("%-22s %-10s %s" % ("pass", "kind", "default"))
    for name in sorted(PASS_REGISTRY):
        cls = PASS_REGISTRY[name]
        kind = getattr(cls, "kind", "checker")
        if kind == "transform":
            on = getattr(cls, "min_level", 2) <= default_level
            note = "on (level>=%d)" % cls.min_level if on else \
                "off (level>=%d)" % cls.min_level
        else:
            note = "on" if name in DEFAULT_PASSES else "off"
        print("%-22s %-10s %s" % (name, kind, note))


def _maybe_optimize(program, args, feed_names=None, fetch_names=None):
    """Apply the transform pipeline when --opt-level was given; returns
    the desc to lint (the transformed clone, or the input unchanged)."""
    if args.opt_level is None:
        return program
    from paddle_tpu.analysis import optimize_program

    desc, report = optimize_program(
        program, level=args.opt_level,
        feed_names=feed_names, fetch_names=fetch_names)
    print(report.render())
    return desc


def _print_memory_plan(program_or_desc, args, fetch_names=None):
    """The --memory report: liveness peak + top contributors, donation
    split, and the remat choice under the requested budget, straight off
    MemoryPlan.render() — the same planner the engine runs at opt 3."""
    from paddle_tpu.analysis import memory as memplan

    if args.budget_mb is not None:
        budget = int(args.budget_mb * (1 << 20))
    else:
        budget = memplan.hbm_budget_bytes()
    plan = memplan.plan_memory(program_or_desc, fetch_names=fetch_names,
                               budget_bytes=budget)
    print("-- memory plan (budget: %s) --"
          % ("%d MiB" % (budget >> 20) if budget else "none"))
    print(plan.render())


def _print_layout_plan(program_or_desc, feed_names=None, fetch_names=None):
    """The --layout report: dry-run the NHWC layout-assignment partition
    (analysis/layout.py plan_layout — no desc mutation, no scope) and
    print what the engine's opt-level-4 compile would do: which ops take
    NHWC, every transpose2 seam and the op it feeds, and the weights
    that would be re-laid-out OIHW->HWIO."""
    from paddle_tpu.analysis.layout import plan_layout

    plan = plan_layout(program_or_desc, feed_names=feed_names or (),
                       fetch_names=fetch_names or ())
    print("-- layout report (NHWC assignment, dry run) --")
    print(plan.render())


def _print_spmd_report(program_or_desc, args, feed_names=None,
                       fetch_names=None):
    """The --spmd report: the static SPMD analysis (analysis/spmd.py)
    under the --mesh/--rule table — sharding table, predicted collective
    schedule with per-collective bytes, per-device peak vs replicated
    peak, and the replicated-optimizer-state (ZeRO-1) ledger. Feed
    shapes come from the desc with dynamic dims resolved to --batch."""
    from paddle_tpu.analysis.spmd import analyze_spmd

    # analyze_spmd is purely static — a {axis: size} dict is enough, no
    # devices are ever touched for the report itself
    mesh = _parse_mesh_axes(args.mesh) or {"dp": 2}
    rules = _parse_rules(args.rule)
    desc = getattr(program_or_desc, "desc", program_or_desc)
    gb = desc.block(0)
    feed_shapes = {}
    for n in (feed_names or ()):
        vd = gb.find_var_recursive(n)
        if vd is not None and vd.shape is not None:
            feed_shapes[n] = tuple(
                args.batch if int(d) < 0 else int(d) for d in vd.shape)
    report = analyze_spmd(desc, mesh=mesh, shard_rules=rules,
                          feed_names=feed_names,
                          feed_shapes=feed_shapes,
                          fetch_names=fetch_names, zero1=args.zero1)
    print("-- spmd report --")
    print(report.render())


def _flags_doc_lint():
    """The --flags mode: cross-reference the README flags table against
    the flags.py DEFS registry (flags.flags_doc_issues) and fail on any
    missing, stale, or duplicated row."""
    from paddle_tpu import flags

    issues = flags.flags_doc_issues()
    if not issues:
        print("flags doc: README table and flags.py DEFS are in sync "
              "(%d flags)" % len(flags.DEFS))
        return 0
    for issue in issues:
        print("flags doc: %s" % issue)
    print("\nflags doc: %d issue(s)" % len(issues))
    return 1


def _provenance_lint():
    """The --provenance mode: three checks over the opprof lowering
    provenance (observability/opprof.py).

    (a) Every registered op type's scope tag survives the full jit path
        join — ``parse_tag("jit(f)/.../pt.<type>.<b>_<i>/hlo")`` must
        recover exactly the tag ``provenance_tag`` emitted.
    (b) A real compile: run the mnist MLP one training step with the
        opprof flag on and metrics enabled, then assert every live
        (post-DCE) op in every compiled executable landed in the
        provenance map, that at least one ``pt.*`` tag reached the
        compiled HLO op_metadata, and that the opprof registry has a
        cost row for every provenance tag.
    (c) Layering: no module under paddle_tpu/ imports from tools/ (the
        library must never depend on the CLI layer — tools/ shims like
        xplane_top_ops.py point the other way).

    Exit 1 on any failure.
    """
    import re

    import numpy as np

    from paddle_tpu import flags
    from paddle_tpu import observability as obs
    from paddle_tpu.core.registry import OpRegistry
    from paddle_tpu.observability import opprof

    issues = []

    # (a) tag round-trip for every registered op type
    types = OpRegistry.all_types()
    for t in types:
        tag = opprof.provenance_tag(t, 0, 3)
        path = "jit(run)/transpose(jvp(run))/%s/dot_general" % tag
        if opprof.parse_tag(path) != tag or opprof.tag_op_type(tag) != t:
            issues.append("op type %r: scope tag %r does not round-trip "
                          "through parse_tag" % (t, tag))
    print("provenance: %d registered op type(s) checked for scope-tag "
          "round-trip" % len(types))

    # (b) live compile coverage on the mnist MLP
    import paddle_tpu.fluid as fluid
    from paddle_tpu import unique_name
    from paddle_tpu.executor import Executor
    from paddle_tpu.framework import Program, program_guard

    builders = _load_book_builders()
    old_gen = unique_name.switch()
    was_enabled = obs.enabled()
    old_opprof = flags.get_flag("opprof")
    try:
        flags.set_flags({"opprof": True})
        obs.set_enabled(True)
        opprof.reset()
        main, startup = Program(), Program()
        with program_guard(main, startup):
            feeds, fetch, loss = builders["mnist_mlp"]()
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = Executor()
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(main,
                    feed={"img": rng.randn(8, 784).astype(np.float32),
                          "label": np.ones((8, 1), np.int64)},
                    fetch_list=[loss.name])
        compiled = [cb for cb in exe.engine._cache.values()
                    if getattr(cb, "provenance", None)]
        if not compiled:
            issues.append("mnist_mlp compile recorded no provenance map "
                          "(opprof flag not threaded through _compile?)")
        live_tags = set()
        for cb in compiled:
            block = cb.block_program.block
            for i, op in enumerate(cb.block_program.ops):
                tag = opprof.provenance_tag(
                    op.type, getattr(block, "idx", 0), i)
                live_tags.add(tag)
                if tag not in cb.provenance:
                    issues.append("live op %s #%d: no provenance entry "
                                  "(expected tag %r)" % (op.type, i, tag))
        snap = opprof.registry_snapshot()
        if not snap["instr_tags"]:
            issues.append("no pt.* scope tag reached the compiled HLO "
                          "op_metadata (named_scope lost in lowering?)")
        missing_costs = sorted(live_tags - set(snap["costs"]))
        for tag in missing_costs:
            issues.append("tag %r has no cost row in the opprof registry "
                          "(register_executable skipped it)" % tag)
        print("provenance: mnist_mlp compiled %d executable(s), %d live "
              "op(s), %d tagged HLO instruction(s), %d cost row(s)"
              % (len(compiled), len(live_tags), len(snap["instr_tags"]),
                 len(snap["costs"])))
    finally:
        flags.set_flags({"opprof": old_opprof})
        obs.set_enabled(was_enabled)
        unique_name.switch(old_gen)

    # (c) layering: the library never imports from the tools/ CLI layer
    pat = re.compile(r"^\s*(?:from\s+tools\b|import\s+tools\b)", re.M)
    n_scanned = 0
    for dirpath, _dirs, files in os.walk(os.path.join(REPO_ROOT,
                                                      "paddle_tpu")):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            n_scanned += 1
            with open(path) as f:
                if pat.search(f.read()):
                    issues.append("%s imports from tools/ (library -> CLI "
                                  "layering violation)"
                                  % os.path.relpath(path, REPO_ROOT))
    print("provenance: %d paddle_tpu module(s) scanned for tools/ imports"
          % n_scanned)

    if not issues:
        print("\nprovenance lint: OK")
        return 0
    for issue in issues:
        print("provenance lint: %s" % issue)
    print("\nprovenance lint: %d issue(s)" % len(issues))
    return 1


def _freeze_report(main, startup, feed_names, fetch_names):
    """The --freeze report: run the real freeze + PTQ pipeline
    (inference/freeze.py, inference/quantize.py) over the built model and
    print the op/var before/after counts, the BN-fold tally, and the
    quantized-vs-skipped table with each op's calibrated activation
    range and weight scale."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.executor import Executor
    from paddle_tpu.inference import freeze_program
    from paddle_tpu.inference.quantize import (
        calibrate_program,
        quantize_desc,
    )

    exe = Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    frozen, rep = freeze_program(main, feed_names, fetch_names,
                                 scope=scope)
    print("-- freeze report --")
    print(rep.render())
    # synthetic calibration feeds off the desc shapes (-1 -> small
    # batch); integer feeds get ones — valid ids for any vocab/label
    # space of size >= 2 and non-degenerate sequence lengths
    gb = main.desc.global_block()
    rng = np.random.RandomState(0)
    feed = {}
    for n in feed_names:
        vd = gb.find_var_recursive(n)
        shape = [4 if int(d) < 0 else int(d)
                 for d in (list(vd.shape) or [4])]
        if "int" in str(vd.dtype).lower():
            feed[n] = np.ones(shape, np.int64)
        else:
            feed[n] = (rng.randn(*shape) * 0.5).astype(np.float32)
    with fluid.scope_guard(scope):
        stats = calibrate_program(frozen, [feed, feed], scope=scope,
                                  executor=exe, max_batches=2)
        work = frozen.desc.clone()
        qrep = quantize_desc(work, scope, stats.ranges())
    print("-- quantization report --")
    print(qrep.render())


def _lint_built_model(name, builder, args):
    from paddle_tpu import unique_name
    from paddle_tpu.analysis import Severity, verify_program
    from paddle_tpu.framework import Program, program_guard

    import paddle_tpu.fluid as fluid

    old_gen = unique_name.switch()
    try:
        main, startup = Program(), Program()
        with program_guard(main, startup):
            feeds, fetch, loss = builder()
            if args.train:
                fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        mesh = _parse_mesh(args.mesh)
        rules = _parse_rules(args.rule)
        fetches = [loss.name, fetch.name]
        print("== %s ==" % name)
        main_desc = _maybe_optimize(main, args, feed_names=feeds,
                                    fetch_names=fetches)
        report = verify_program(
            main_desc, feed_names=feeds,
            fetch_names=fetches,
            mesh=mesh, shard_rules=rules)
        startup_report = verify_program(startup)
        report.extend(startup_report.findings)
        if args.memory:
            _print_memory_plan(main_desc, args, fetch_names=fetches)
        if args.layout:
            _print_layout_plan(main_desc, feed_names=feeds,
                               fetch_names=fetches)
        if args.spmd:
            _print_spmd_report(main_desc, args, feed_names=feeds,
                               fetch_names=fetches)
        if args.freeze:
            try:
                _freeze_report(main, startup, feeds, [fetch.name])
            except Exception as e:  # per-model: a freeze failure is a
                # report line, not a lint abort
                print("-- freeze report failed: %s: %s --"
                      % (type(e).__name__, e))
    finally:
        unique_name.switch(old_gen)

    min_sev = Severity.INFO if args.verbose else Severity.WARNING
    print(report.render(min_severity=min_sev))
    return report


def _lint_file(path, args):
    from paddle_tpu.analysis import Severity, verify_program
    from paddle_tpu.core.desc import ProgramDescData
    from paddle_tpu.framework import Program

    with open(path, "rb") as f:
        blob = f.read()
    program = None
    try:
        program = Program.parse_from_string(blob)
    except Exception:
        obj = pickle.loads(blob)
        if isinstance(obj, (bytes, str)):
            program = Program.parse_from_string(obj)
        elif isinstance(obj, ProgramDescData):
            program = obj
        else:
            program = obj  # a pickled Program
    print("== %s ==" % path)
    program = _maybe_optimize(program, args)
    report = verify_program(program, mesh=_parse_mesh(args.mesh),
                            shard_rules=_parse_rules(args.rule))
    if args.memory:
        _print_memory_plan(program, args)
    if args.layout:
        _print_layout_plan(program)
    if args.spmd:
        _print_spmd_report(program, args)
    min_sev = Severity.INFO if args.verbose else Severity.WARNING
    print(report.render(min_severity=min_sev))
    return report


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Static program linter (paddle_tpu.analysis)")
    parser.add_argument("--program", metavar="FILE",
                        help="serialized/pickled program to lint")
    parser.add_argument("--model", action="append", default=[],
                        help="book model name to build and lint "
                             "(repeatable; default: all)")
    parser.add_argument("--no-train", dest="train", action="store_false",
                        help="lint the forward program only (skip "
                             "append_backward + optimizer)")
    parser.add_argument("--mesh", default="",
                        help="mesh axes for the sharding checker, e.g. "
                             "dp=4,tp=2")
    parser.add_argument("--rule", action="append", default=[],
                        help="sharding rule PATTERN:axis0,axis1 "
                             "(repeatable; empty slot = unsharded dim)")
    parser.add_argument("--opt-level", type=int, default=None,
                        metavar="N",
                        help="run the transform pipeline at level N and "
                             "lint the transformed desc (0 off, 1 "
                             "fuse-attention, 2 + fusion/folding/cse)")
    parser.add_argument("--memory", action="store_true",
                        help="print each main program's memory plan "
                             "(liveness peak + top contributors, "
                             "donation split, remat choice) after "
                             "linting it")
    parser.add_argument("--budget-mb", type=float, default=None,
                        metavar="MB",
                        help="HBM budget for the --memory remat policy "
                             "(default: device limit x "
                             "PADDLE_TPU_HBM_BUDGET_FRAC, if knowable)")
    parser.add_argument("--layout", action="store_true",
                        help="print each program's NHWC layout-"
                             "assignment plan (analysis/layout.py dry "
                             "run): ops assigned NHWC, transpose seams "
                             "and where they land, weights re-laid-out "
                             "OIHW->HWIO")
    parser.add_argument("--freeze", action="store_true",
                        help="after linting each built model, run the "
                             "inference freeze + INT8 PTQ pipeline over "
                             "it and print the op/var before/after "
                             "counts, BN folds, and the quantized-vs-"
                             "skipped table with calibrated ranges")
    parser.add_argument("--spmd", action="store_true",
                        help="print each program's static SPMD report "
                             "(analysis/spmd.py) under --mesh/--rule "
                             "(default mesh dp=2): sharding table, "
                             "predicted collective schedule with bytes, "
                             "per-device peak vs replicated peak, and "
                             "the replicated-optimizer-state ledger")
    parser.add_argument("--batch", type=int, default=8, metavar="N",
                        help="batch size used to resolve dynamic feed "
                             "dims for --spmd (default 8)")
    parser.add_argument("--zero1", action="store_true",
                        help="analyze --spmd with the ZeRO-1 sharded "
                             "weight update on (PADDLE_TPU_ZERO "
                             "semantics): the schedule gains the per-"
                             "param all-gathers and the optimizer-state "
                             "ledger reads post-sharding")
    parser.add_argument("--flags", action="store_true",
                        help="cross-reference the README flags table "
                             "against the flags.py DEFS registry and "
                             "exit 1 on missing/stale/duplicate rows")
    parser.add_argument("--provenance", action="store_true",
                        help="lint the opprof lowering provenance: every "
                             "registered op type's scope tag round-trips "
                             "through parse_tag, a real mnist_mlp compile "
                             "covers every live op with a tagged HLO "
                             "cost row, and no paddle_tpu module imports "
                             "from tools/")
    parser.add_argument("--list-passes", action="store_true",
                        help="list every registered pass (name, kind, "
                             "default on/off) and exit")
    parser.add_argument("--timing", action="store_true",
                        help="collect per-pass wall time via the "
                             "telemetry registry (paddle_tpu."
                             "observability) and print the table after "
                             "linting")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="show INFO findings too")
    args = parser.parse_args(argv)

    if args.list_passes:
        _list_passes()
        return 0

    if args.flags:
        return _flags_doc_lint()

    if args.provenance:
        return _provenance_lint()

    if args.mesh:
        # a Mesh over N>1 axes needs N host devices; force them before
        # jax initializes (lint never touches real accelerators)
        total = 1
        for size in (_parse_mesh_axes(args.mesh) or {}).values():
            total *= max(size, 1)
        if total > 1 and "xla_force_host_platform_device_count" \
                not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=%d" % total)

    if args.timing:
        from paddle_tpu import observability

        observability.set_enabled(True)

    reports = []
    if args.program:
        reports.append(_lint_file(args.program, args))
    else:
        builders = _load_book_builders()
        names = args.model or sorted(builders)
        for name in names:
            if name not in builders:
                raise SystemExit(
                    "unknown model %r; known: %s" % (name, sorted(builders)))
            reports.append(_lint_built_model(name, builders[name], args))

    if args.timing:
        _print_timing()

    n_err = sum(len(r.errors) for r in reports)
    n_warn = sum(len(r.warnings) for r in reports)
    print("\nlint: %d program(s), %d error(s), %d warning(s)"
          % (len(reports), n_err, n_warn))
    return 1 if n_err else 0


def _print_timing():
    """Per-pass wall-time table from the telemetry registry: every
    ``analysis.<checker>.ms`` and ``transform.<pass>.ms`` histogram the
    lint run filled."""
    from paddle_tpu import observability

    hists = observability.snapshot()["histograms"]
    rows = [(name, h) for name, h in sorted(hists.items())
            if name.startswith(("analysis.", "transform."))]
    print("\n== per-pass timings ==")
    if not rows:
        print("(no pass timings recorded)")
        return
    print("%-36s %6s %10s %10s" % ("pass", "calls", "total ms", "mean ms"))
    for name, h in rows:
        print("%-36s %6d %10.2f %10.2f"
              % (name[:-3] if name.endswith(".ms") else name,
                 h["count"], h["total"], h["mean"] or 0.0))


if __name__ == "__main__":
    sys.exit(main())
