#!/usr/bin/env python
"""Aggregate device-time by op from a jax.profiler xplane trace — the
trace-reading half of the profiler story (SURVEY §5), used in round 4 to
find where the BERT engine step spends its time vs the probe.

Usage: PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
           python tools/xplane_top_ops.py <trace_dir> [top_n] [group]
``group``: 'op' (default, per fused-computation name) or 'kind'
(collapse to the HLO opcode-ish prefix, e.g. fusion/copy/convolution).
"""
import glob
import re
import sys
from collections import defaultdict


def iter_planes(trace_dir):
    """Yield every non-empty DISTINCT plane from the .xplane.pb files
    under ``trace_dir`` (shared by this tool and tools/timeline.py).
    Byte-identical planes are skipped — some sessions embed the same
    device plane in more than one dump file, which would double every
    aggregate — while genuine multi-host planes (same name, different
    events/timestamps) all pass through."""
    import hashlib

    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    files = sorted(glob.glob("%s/**/*.xplane.pb" % trace_dir,
                             recursive=True))
    if not files:
        raise FileNotFoundError("no xplane.pb under %s" % trace_dir)
    seen = set()
    for f in files:
        xs = xplane_pb2.XSpace()
        with open(f, "rb") as fh:
            xs.ParseFromString(fh.read())
        for plane in xs.planes:
            if not sum(len(l.events) for l in plane.lines):
                continue
            digest = hashlib.sha256(
                plane.SerializeToString(deterministic=True)).digest()
            if digest in seen:
                continue
            seen.add(digest)
            yield plane


def top_ops(trace_dir, top_n=25, group="op"):
    per = defaultdict(float)
    total = 0.0
    # aggregate over every host's trace file and every device plane
    # (multi-core chips emit one plane per core)
    for plane in iter_planes(trace_dir):
        if "/device:" in plane.name:
            meta = {m.id: m.name for m in plane.event_metadata.values()}
            for line in plane.lines:
                if line.name != "XLA Ops":
                    continue
                for e in line.events:
                    name = meta.get(e.metadata_id, "?")
                    if group == "kind":
                        name = re.split(r"[.\d]", name, 1)[0]
                    per[name] += e.duration_ps / 1e9
                    total += e.duration_ps / 1e9
    rows = sorted(per.items(), key=lambda kv: -kv[1])[:top_n]
    return rows, total


if __name__ == "__main__":
    d = sys.argv[1]
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    g = sys.argv[3] if len(sys.argv) > 3 else "op"
    rows, total = top_ops(d, n, g)
    print("total XLA-op device ms: %.2f" % total)
    for name, ms in rows:
        print("%8.2f ms  %5.1f%%  %s" % (ms, ms / total * 100, name[:90]))
