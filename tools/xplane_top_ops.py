#!/usr/bin/env python
"""Aggregate device-time by op from a jax.profiler xplane trace — the
trace-reading half of the profiler story (SURVEY §5), used in round 4 to
find where the BERT engine step spends its time vs the probe.

Usage: PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
           python tools/xplane_top_ops.py <trace_dir> [top_n] [group]
``group``: 'op' (default, per fused-computation name) or 'kind'
(collapse to the HLO opcode-ish prefix, e.g. fusion/copy/convolution).
"""
import glob
import re
import sys
from collections import defaultdict


def top_ops(trace_dir, top_n=25, group="op"):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    files = glob.glob("%s/**/*.xplane.pb" % trace_dir, recursive=True)
    assert files, "no xplane.pb under %s" % trace_dir
    per = defaultdict(float)
    total = 0.0
    # aggregate over every host's trace file and every device plane
    # (multi-core chips emit one plane per core)
    for f in files:
        xs = xplane_pb2.XSpace()
        xs.ParseFromString(open(f, "rb").read())
        planes = [p for p in xs.planes if "/device:" in p.name
                  and sum(len(l.events) for l in p.lines)]
        for plane in planes:
            meta = {m.id: m.name for m in plane.event_metadata.values()}
            for line in plane.lines:
                if line.name != "XLA Ops":
                    continue
                for e in line.events:
                    name = meta.get(e.metadata_id, "?")
                    if group == "kind":
                        name = re.split(r"[.\d]", name, 1)[0]
                    per[name] += e.duration_ps / 1e9
                    total += e.duration_ps / 1e9
    rows = sorted(per.items(), key=lambda kv: -kv[1])[:top_n]
    return rows, total


if __name__ == "__main__":
    d = sys.argv[1]
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    g = sys.argv[3] if len(sys.argv) > 3 else "op"
    rows, total = top_ops(d, n, g)
    print("total XLA-op device ms: %.2f" % total)
    for name, ms in rows:
        print("%8.2f ms  %5.1f%%  %s" % (ms, ms / total * 100, name[:90]))
