#!/usr/bin/env python
"""Aggregate device-time by op from a jax.profiler xplane trace — the
trace-reading half of the profiler story (SURVEY §5), used in round 4 to
find where the BERT engine step spends its time vs the probe.

Thin CLI shim: the plane iterator and aggregation live in
``paddle_tpu.observability.opprof`` (the package must never import from
tools/); ``iter_planes``/``top_ops`` are re-exported here for
back-compat with older scripts.

Usage: PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python \
           python tools/xplane_top_ops.py <trace_dir> [top_n] [group]
``group``: 'op' (default, per fused-computation name) or 'kind'
(collapse to the HLO opcode-ish prefix, e.g. fusion/copy/convolution).
"""
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.observability.opprof import (  # noqa: E402,F401
    iter_planes,
    top_ops,
)

if __name__ == "__main__":
    d = sys.argv[1]
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    g = sys.argv[3] if len(sys.argv) > 3 else "op"
    rows, total = top_ops(d, n, g)
    print("total XLA-op device ms: %.2f" % total)
    for name, ms in rows:
        print("%8.2f ms  %5.1f%%  %s" % (ms, ms / total * 100, name[:90]))
