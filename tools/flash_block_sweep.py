#!/usr/bin/env python
"""Sweep flash-attention block sizes on the real chip and emit the
committed autotune table consumed by ``pick_block`` (VERDICT r3 Next #9:
replace the one-off hand tune with a table from a reproducible sweep;
the discipline of the reference's jit kernel benchmarks,
benchmark/paddle/fluid/operators/jit/README.en.md).

Protocol: the same MARGINAL-cost measurement as ``bench.py``'s flash
bench — on the tunneled chip a single drained window carries ~1-2.5s of
session-variable dispatch/readback overhead that dwarfs the ms-scale
kernels, so each (dtype, seq, block) config runs as one jitted
``lax.fori_loop`` of chained fwd+bwd steps at TWO loop counts; per-step
device time = (T_hi - T_lo)/Δn (overhead subtracts out), diff-of-medians
over ``reps`` interleaved rounds. Δn is sized from a FLOP model so every
config's signal is ~3s. Configs that fail to compile (VMEM OOM at wide
blocks x long f32 seqs) are skipped; the table is dumped incrementally
after every (dtype, seq) row so a late failure cannot lose the sweep.

Writes paddle_tpu/kernels/flash_block_table.json:
    {"bfloat16": {"256": best_block, ...}, "float32": {...}}
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), os.pardir)))

OUT = os.path.abspath(os.path.join(
    os.path.dirname(__file__), os.pardir, "paddle_tpu", "kernels",
    "flash_block_table.json"))


from tools.marginal_timing import (chained_grad_loop,  # noqa: E402
                                   run_marginal_protocol)


def _dump(table):
    with open(OUT, "w") as f:
        json.dump(table, f, indent=1, sort_keys=True)


DEFAULT_BLOCKS = (128, 256, 512, 1024)


def sweep(seqs=(256, 512, 1024, 2048, 4096), blocks=DEFAULT_BLOCKS,
          dtypes=("bfloat16", "float32"), batch=4, heads=16, dim=64,
          reps=3, target_signal_s=3.0, fresh=False):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.kernels.flash_attention import flash_attention

    assert jax.default_backend() != "cpu", "sweep needs the TPU backend"
    # merge into the existing table so a partial re-sweep (one row, more
    # reps) refines rather than clobbers the committed winners;
    # fresh=True regenerates from scratch
    table = {}
    if not fresh:
        try:
            with open(OUT) as f:
                table = json.load(f)
        except (OSError, ValueError):
            pass
    for dtype in dtypes:
        table.setdefault(dtype, {})
        for seq in seqs:
            rng = np.random.RandomState(0)
            # long f32 runs blow HBM sooner; shrink batch at 4096
            b = batch if seq < 4096 else max(1, batch // 2)
            q, k, v = (jax.device_put(jnp.asarray(
                rng.randn(b, heads, seq, dim), dtype)) for _ in range(3))
            # fwd+bwd ~ 3.5 x 4*B*H*T^2*D FLOPs; assume >=20 TFLOP/s so
            # Δn errs toward a LONGER (higher-signal) window
            est_s = 3.5 * 4 * b * heads * seq * seq * dim / 20e12
            dn = int(min(4096, max(64, target_signal_s / est_s)))
            n_lo, n_hi = 4, 4 + dn
            variants = {}
            any_tiled = False
            for blk in blocks:
                if seq % blk:
                    continue
                any_tiled = True
                g = jax.grad(
                    lambda a, c, d, _blk=blk: jnp.sum(flash_attention(
                        a, c, d, None, 0, True, None, 0.0, _blk, _blk,
                        False).astype(jnp.float32)),
                    argnums=(0, 1, 2))
                try:
                    # compile-check the SHORT window only: VMEM fit
                    # depends on the block config, not the trip count,
                    # and the protocol warms both windows itself
                    fn_lo = chained_grad_loop(g, n_lo)
                    jax.device_get(fn_lo(q, k, v))
                except Exception as e:              # noqa: BLE001
                    print("dtype=%s seq=%d block %d skipped: %s"
                          % (dtype, seq, blk, str(e)[:100]), flush=True)
                    continue
                variants[blk] = (fn_lo, n_lo,
                                 chained_grad_loop(g, n_hi), n_hi)
            if not variants:
                if not any_tiled:
                    # no candidate even tiles this seq (e.g. a narrow
                    # --blocks selection) — that's a no-measurement, not
                    # a failure; the committed row must survive
                    print("dtype=%s seq=%d: no candidate tiles, row "
                          "kept" % (dtype, seq), flush=True)
                    continue
                print("dtype=%s seq=%d: no block compiled, row dropped"
                      % (dtype, seq), flush=True)
                # a stale committed winner measured under an older
                # kernel must not survive a run where nothing compiles
                table[dtype].pop(str(seq), None)
                _dump(table)
                continue
            measured = run_marginal_protocol(variants, (q, k, v), reps)
            # a non-positive marginal is an overhead spike, not a kernel
            # time — it must never be crowned the winner
            med = {blk: m for blk, (m, _) in measured.items() if m > 0}
            if not med:
                print("dtype=%s seq=%d: all marginals drowned in "
                      "overhead noise, row dropped" % (dtype, seq),
                      flush=True)
                table[dtype].pop(str(seq), None)
                _dump(table)
                continue
            best = min(med, key=med.get)
            table[dtype][str(seq)] = best
            print("dtype=%s seq=%d dn=%d -> block %d   %s" % (
                dtype, seq, dn, best,
                " ".join("%d:%.3fms" % (b_, m * 1e3)
                         for b_, m in sorted(med.items()))), flush=True)
            _dump(table)                             # incremental dump
    return table


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(
        "flash_block_sweep",
        description="Re-sweep all rows, or --seqs/--dtypes for one row "
                    "with more --reps; winners merge into the table.")
    ap.add_argument("--seqs", type=int, nargs="+",
                    default=[256, 512, 1024, 2048, 4096,
                             8192, 16384])
    ap.add_argument("--dtypes", nargs="+",
                    default=["bfloat16", "float32"])
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--fresh", action="store_true",
                    help="ignore the existing table, regenerate")
    ap.add_argument("--blocks", type=int, nargs="+",
                    default=list(DEFAULT_BLOCKS),
                    help="candidate block sizes (the streamed kernels "
                         "keep VMEM bounded by block size, so wide "
                         "candidates like 1024 are in the default set "
                         "— a default re-sweep must never clobber a "
                         "committed wide-block winner)")
    a = ap.parse_args()
    sweep(seqs=tuple(a.seqs), dtypes=tuple(a.dtypes), reps=a.reps,
          blocks=tuple(a.blocks), fresh=a.fresh)
    print("wrote", OUT)
