#!/usr/bin/env python
"""Serving SLO probe: Poisson load sweep against the continuous-batching
InferenceServer (paddle_tpu.inference.serving).

Builds a tiny model, freezes it (training ops stripped, BN folded),
optionally INT8-quantizes it (default on — the production serving
configuration), then drives the server with a Poisson arrival process at
each requested QPS level: exponential inter-arrival gaps from a seeded
RNG, one-row requests submitted asynchronously so queueing behavior is
the server's own (the driver never throttles on responses; every future
is drained before the level is scored).

The per-level QPS / p50 / p99 / queue-depth table is assembled FROM THE
TELEMETRY SINKS, not from driver-side stopwatches: each level attaches a
fresh observability JsonlSink, the server's ``serving.*`` histograms
stream into it, and the probe parses the final snapshot event back out —
the same files a fleet run would ship, so the probe doubles as an
end-to-end test of the serving SLO export path (the shape of
multichip_probe.py's gauge round-trip, extended to histograms).

``--slo-ms X --slo-floor-qps Y`` is the CI gate: the probe finds the
highest offered-load level whose p99 still meets X ms and exits non-zero
when that level's achieved QPS lands below Y — "the serving path stopped
meeting its latency budget" as a red build, the serving twin of
multichip_probe's ``--efficiency-floor``.

Usage:
  python tools/serve_probe.py --model mlp --qps 5,10,20
  python tools/serve_probe.py --model resnet50 --no-int8 --duration 3
  python tools/serve_probe.py --qps 4,8 --slo-ms 100 --slo-floor-qps 4
  python tools/serve_probe.py --qps 8 --check-health   # readiness flip
  python tools/serve_probe.py --autoscale              # elastic fleet:
      # spike trips the fast burn window, the FleetRouter scales out
      # before the slow window confirms, p99 recovers, nothing dropped
  python tools/serve_probe.py --trace                  # tracing gate:
      # every over-SLO request under 2x-capacity load leaves a kept
      # trace whose span-sum matches the measured latency; a calm run
      # keeps ~only head-sampled traces (see tools/trace_query.py)
"""

import argparse
import json
import os
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

# Probe on the host CPU backend; never grabs TPU devices.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

MODELS = ("mlp", "resnet50", "bert")


def _build(model, seed):
    """(main, startup, feed_names, fetch_names, one_row_fn) on tiny CPU
    geometry — the probe measures the batcher and the SLO pipeline, not
    the chip."""
    import numpy as np

    from paddle_tpu import models

    rng = np.random.RandomState(seed)
    if model == "mlp":
        main, startup, h = models.mnist.get_model(lr=0.01)

        def one_row():
            return {"img": rng.randn(1, 784).astype(np.float32)}

        return main, startup, ["img"], [h["logits"].name], one_row
    if model == "resnet50":
        # cifar resnet at depth 20: the real conv/BN graph (BN folding +
        # per-channel conv quantization exercised) without imagenet-sized
        # CPU step times — the multichip_probe naming convention
        main, startup, h = models.resnet.get_model(
            dataset="cifar10", depth=20, class_num=10, lr=0.1)

        def one_row():
            return {"img": rng.randn(1, 3, 32, 32).astype(np.float32)}

        return main, startup, ["img"], [h["logits"].name], one_row
    if model == "bert":
        kw = dict(d_model=64, n_layers=2, n_heads=2, d_inner=128)
        main, startup, h = models.bert.get_model(
            batch_size=4, seq_len=32, vocab_size=512, dropout=0.0,
            lr=1e-4, max_position=512, **kw)
        enc_feeds = ["src_ids", "pos_ids", "sent_ids", "seq_lens"]

        def one_row():
            b = models.bert.make_fake_batch(1, 32, 512, kw["n_heads"],
                                            rng=rng)
            return {k: b[k] for k in enc_feeds}

        return main, startup, enc_feeds, [h["enc_out"].name], one_row
    raise ValueError("unknown model %r (want one of %s)" % (model, MODELS))


def build_server(model="mlp", int8=True, calib_batches=4, buckets=None,
                 max_wait_ms=None, seed=0, slo_ms=None, slo_monitor=None):
    """Freeze (+quantize) the model and wrap it in an InferenceServer
    (not yet started). Returns (server, one_row_fn, build_info)."""
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.executor import Executor
    from paddle_tpu.inference import (
        InferenceServer,
        freeze_program,
        post_training_quantize,
    )

    main, startup, feed_names, fetch_names, one_row = _build(model, seed)
    exe = Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    frozen, freeze_rep = freeze_program(
        main, feed_names, fetch_names, scope=scope)
    info = {"model": model, "freeze": freeze_rep.render(),
            "bn_folds": freeze_rep.bn_folds, "int8": bool(int8)}
    program = frozen
    if int8:
        batches = []
        for _ in range(calib_batches):
            rows = [one_row() for _ in range(4)]
            batches.append({k: np.concatenate([r[k] for r in rows])
                            for k in feed_names})
        program, _, qrep = post_training_quantize(
            frozen, batches, feed_names, fetch_names, scope=scope,
            executor=exe, max_batches=calib_batches)
        info["quantized_ops"] = len(qrep.quantized)
        info["skipped_ops"] = len(qrep.skipped)
    server = InferenceServer(program, feed_names, fetch_names, scope=scope,
                             executor=exe, buckets=buckets,
                             max_wait_ms=max_wait_ms, name="probe",
                             slo_ms=slo_ms, slo_monitor=slo_monitor)
    return server, one_row, info


def _poisson_level(server, one_row, qps, duration, rng):
    """Offer ``qps`` for ``duration`` seconds with exponential gaps;
    drain every future. Returns (n_requests, elapsed_seconds)."""
    futures = []
    t0 = time.monotonic()
    t_end = t0 + duration
    next_t = t0
    while True:
        next_t += rng.exponential(1.0 / qps)
        if next_t >= t_end:
            break
        delay = next_t - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        futures.append(server.submit(one_row()))
    for f in futures:
        f.result(timeout=600)
    return len(futures), time.monotonic() - t0


def _read_sink_serving(path):
    """serving.* histograms + counters from the last metrics snapshot of
    a JSONL sink file (detach_sink emits one on exit)."""
    metrics = None
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("t") == "snap":
                    metrics = ev.get("metrics") or metrics
    except OSError:
        return None
    if not metrics:
        return None
    return {"histograms": metrics.get("histograms") or {},
            "counters": metrics.get("counters") or {}}


def probe_serving(server, one_row, qps_levels, duration=2.0, seed=0,
                  sink_dir=None, health_log=None):
    """Run the sweep; returns a list of per-level dicts (scored from the
    telemetry sinks). Each row also carries the ``server.health()``
    readiness snapshot taken right after its level; when ``health_log``
    is a list, the pre-load baseline snapshot is appended to it."""
    import numpy as np

    from paddle_tpu import observability as obs

    if sink_dir is None:
        sink_dir = tempfile.mkdtemp(prefix="serve_probe_")
    obs.set_enabled(True)
    rows = []
    with server:
        server.warmup(one_row())
        if health_log is not None:
            health_log.append(server.health())
        for qps in qps_levels:
            sink = os.path.join(sink_dir, "serve_qps%g.jsonl" % qps)
            obs.reset()
            obs.attach_sink(sink)
            rng = np.random.RandomState(seed)
            n, elapsed = _poisson_level(server, one_row, qps, duration,
                                        rng)
            # readiness snapshot BEFORE leaving the context: health()
            # needs the worker thread alive to mean anything
            health = server.health()
            obs.detach_sink()
            m = _read_sink_serving(sink) or {"histograms": {},
                                             "counters": {}}
            req = m["histograms"].get("serving.request_ms") or {}
            depth = m["histograms"].get("serving.queue_depth") or {}
            fill = m["histograms"].get("serving.batch_fill") or {}
            rows.append({
                "qps_offered": qps,
                "qps_achieved": n / elapsed if elapsed else 0.0,
                "requests": n,
                "served": int(m["counters"].get("serving.requests", 0)),
                "batches": int(m["counters"].get("serving.batches", 0)),
                "p50_ms": req.get("p50"),
                "p99_ms": req.get("p99"),
                "queue_depth_mean": depth.get("mean"),
                "batch_fill_mean": fill.get("mean"),
                "health": health,
            })
    obs.set_enabled(None)
    return rows


def render_table(rows):
    hdr = "%-10s %-10s %-8s %-9s %-9s %-11s %s" % (
        "offered", "achieved", "batches", "p50 ms", "p99 ms",
        "queue", "fill")
    out = [hdr]
    for r in rows:
        out.append("%-10g %-10.2f %-8d %-9s %-9s %-11s %s" % (
            r["qps_offered"], r["qps_achieved"], r["batches"],
            _fmt(r["p50_ms"]), _fmt(r["p99_ms"]),
            _fmt(r["queue_depth_mean"]), _fmt(r["batch_fill_mean"])))
    return "\n".join(out)


def _fmt(v):
    return "%.2f" % v if isinstance(v, (int, float)) else "-"


def probe_autoscale(args):
    """Elastic-serving acceptance gate (--autoscale): a FleetRouter over
    per-worker InferenceServers must scale OUT on a load spike's FAST
    burn window — while the SLOW window is still under its threshold,
    i.e. before the incident would page — and p99 must return under the
    SLO on the grown fleet without dropping a single request.

    Timeline: calibrate a baseline p50 on the 1-worker fleet and derive
    the SLO from it (unless --serving-slo-ms pins one), run a calm phase
    (no scaling expected), then burst requests until the router reacts,
    then a recovery phase whose p99 is the verdict. Worker SLO monitors
    use probe-scale windows (seconds, not SRE minutes) so the whole
    story runs in CI time.
    """
    import numpy as np

    from paddle_tpu import observability as obs
    from paddle_tpu.observability.health import SloMonitor
    from paddle_tpu.resilience.elastic import FleetRouter

    obs.set_enabled(True)
    # generous placeholder SLO during calibration; tightened (on every
    # live monitor — slo_ms is read at record time) once measured
    slo_holder = [args.serving_slo_ms or 10000.0]
    monitors = []
    one_row_holder = []

    # row-at-a-time dispatch unless the caller picked buckets: the gate
    # exercises the AUTOSCALER, and a wide-open continuous batcher on a
    # tiny model absorbs any burst a Python driver can offer
    buckets = args.buckets if args.buckets is not None else "1"

    def factory(idx):
        mon = SloMonitor(slo_holder[0], target=0.9, fast_window_s=1.5,
                         slow_window_s=45.0, fast_burn=2.0, slow_burn=3.0,
                         name="probe%d" % idx)
        monitors.append(mon)
        server, one_row, _ = build_server(
            args.model, int8=args.int8, calib_batches=args.calib_batches,
            buckets=buckets, max_wait_ms=args.max_wait_ms,
            seed=args.seed, slo_monitor=mon)
        server.start()
        server.warmup(one_row())     # arrive pre-compiled
        one_row_holder.append(one_row)
        return server

    records = []                     # (phase, latency_ms, exception)

    def submit(router, phase):
        t0 = time.monotonic()
        fut = router.submit(one_row_holder[0]())

        def _done(f, t0=t0, phase=phase):
            records.append((phase, (time.monotonic() - t0) * 1000.0,
                            f.exception()))
        fut.add_done_callback(_done)
        return fut

    def p_of(phase, q):
        lat = [l for p, l, e in records if p == phase and e is None]
        return float(np.percentile(lat, q)) if lat else None

    router = FleetRouter(factory, min_workers=1,
                         max_workers=args.fleet_max, cooldown_s=3.0)
    router.start(poll_interval_s=0.15)
    try:
        # -- calibrate: sequential requests, unloaded 1-worker fleet
        for _ in range(30):
            submit(router, "calib").result(timeout=60)
        baseline_p50 = p_of("calib", 50)
        if args.serving_slo_ms is None:
            slo_holder[0] = max(25.0, 8.0 * baseline_p50)
        slo_ms = slo_holder[0]
        for m in monitors:
            m.slo_ms = slo_ms
        # -- calm phase: in-SLO load, scaling must hold still. This is
        # also the slow window's base of good samples — the spike must
        # trip the FAST window while the slow one still reads healthy,
        # which needs a real history of met requests behind it.
        t_end = time.monotonic() + 4.0
        while time.monotonic() < t_end:
            submit(router, "calm").result(timeout=60)
            time.sleep(0.003)
        calm_scale_outs = router.scale_outs
        # -- spike: a sustained stream at ~2x one worker's capacity —
        # the queue grows, completions blow the SLO, the fast window
        # burns, and the router must react (or the deadline passes)
        t_spike = time.monotonic()
        reaction_s = None
        spike_futures = []
        deadline = t_spike + 15.0
        gap = max(0.0005, baseline_p50 / 1000.0 / 2.0)
        i = 0
        while time.monotonic() < deadline:
            spike_futures.append(submit(router, "spike"))
            i += 1
            if i % 20 == 0 and router.scale_outs > calm_scale_outs:
                reaction_s = time.monotonic() - t_spike
                break
            time.sleep(gap)
        burn_at_scale_out = router.last_scale_out_burn
        for f in spike_futures:
            f.result(timeout=120)    # queue must fully drain, no drops
        # -- recovery: same calm load, on the grown fleet
        t_end = time.monotonic() + 3.0
        while time.monotonic() < t_end:
            submit(router, "recover").result(timeout=60)
            time.sleep(0.003)
        fleet = router.stats()
    finally:
        router.stop()
    obs.set_enabled(None)

    drops = [(p, str(e)) for p, _, e in records if e is not None]
    p99_recovered = p_of("recover", 99)
    slow_quiet = bool(
        burn_at_scale_out is not None
        and burn_at_scale_out["burn_slow"]
        < burn_at_scale_out["slow_threshold"])
    verdict = {
        "slo_ms": round(slo_ms, 2),
        "baseline_p50_ms": round(baseline_p50, 2),
        "calm_scale_outs": calm_scale_outs,
        "scale_outs": fleet["scale_outs"],
        "reaction_s": round(reaction_s, 2) if reaction_s else None,
        "burn_at_scale_out": burn_at_scale_out,
        "scaled_before_slow_window": slow_quiet,
        "spike_p99_ms": round(p_of("spike", 99) or 0.0, 2),
        "recovered_p99_ms": (round(p99_recovered, 2)
                             if p99_recovered is not None else None),
        "requests": len(records),
        "dropped": len(drops),
    }
    verdict["ok"] = bool(
        calm_scale_outs == 0
        and fleet["scale_outs"] >= 1
        and slow_quiet
        and p99_recovered is not None and p99_recovered <= slo_ms
        and not drops)
    print("autoscale: " + json.dumps(verdict))
    if not verdict["ok"]:
        sys.stderr.write(
            "serving autoscale gate failed: want a scale-out on the "
            "fast burn window (slow window still quiet), p99 back under "
            "%.1fms on the grown fleet, and zero drops\n" % slo_ms)
        return 1
    return 0


def probe_overload(args):
    """Overload-protection acceptance gate (--overload): drive the
    server at 4x its measured capacity for a sustained window with the
    admission stack armed (bounded queue + deadlines + priority
    shedding) and assert the three graceful-degradation invariants:

    * bounded — the queue depth never exceeds PADDLE_TPU_QUEUE_LIMIT
      (sampled throughout the overload phase);
    * conserved — every submission is accounted for exactly once:
      served, ``Rejected`` (queue_full / predicted_late / shed), or
      ``DeadlineExceeded``; zero futures are left unresolved;
    * useful — the p99 of ADMITTED-and-served requests stays within
      the SLO. That is the entire point of shedding: the requests you
      do serve stay fast, instead of everyone timing out together.

    Requests carry a queueing deadline of 0.6x the SLO — the deadline
    bounds time-in-queue (checked when the batcher collects), so the
    client budget must leave headroom for the batch compute that
    happens after admission — and a mixed priority population (1 in 4
    high); low-priority traffic is what the gate sheds first once the
    burn monitor trips.
    """
    import numpy as np

    from paddle_tpu import flags
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import DeadlineExceeded, Rejected
    from paddle_tpu.observability.health import SloMonitor

    queue_limit = args.queue_limit
    obs.set_enabled(True)
    flags.set_flags({"metrics": True, "queue_limit": queue_limit,
                     "serving_shed": True})
    try:
        # probe-scale burn windows (seconds, not SRE minutes) so the
        # shedding story fits in CI time; slo_ms is tightened after
        # calibration (read at record time)
        mon = SloMonitor(10000.0, target=0.9, fast_window_s=1.0,
                         slow_window_s=30.0, fast_burn=1.5,
                         slow_burn=3.0, name="overload")
        server, one_row, _ = build_server(
            args.model, int8=args.int8,
            calib_batches=args.calib_batches,
            buckets=args.buckets or "1,2,4",
            max_wait_ms=args.max_wait_ms, seed=args.seed,
            slo_monitor=mon)
        rng = np.random.RandomState(args.seed)
        with server:
            server.warmup(one_row())
            # -- calibrate: single-row p50 and full-bucket batch time
            lat = []
            for _ in range(20):
                t0 = time.monotonic()
                server.run(one_row())
                lat.append((time.monotonic() - t0) * 1000.0)
            p50 = float(np.median(lat))
            slo_ms = args.serving_slo_ms or max(50.0, 10.0 * p50)
            mon.slo_ms = slo_ms
            top = server.buckets[-1]
            t0 = time.monotonic()
            for _ in range(3):
                server.run({k: server._tile(np.asarray(v), top)
                            for k, v in one_row().items()})
            batch_ms = (time.monotonic() - t0) * 1000.0 / 3.0
            # honest capacity of the coalescing batcher: a full top
            # bucket per batch
            cap_qps = top / max(batch_ms, 1e-3) * 1000.0

            # -- sustained overload at 4x capacity (escalating once if
            # CPU timing noise swallowed the pressure)
            duration = max(4.0, 2.0 * args.duration)
            outcome = None
            for mult in (4.0, 16.0):
                qps = mult * cap_qps
                served, shed, expired = [], 0, 0
                rejected = {"queue_full": 0, "predicted_late": 0,
                            "shed": 0}
                futures, depth_max, unresolved, other = [], 0, 0, []
                t_start = time.monotonic()
                t_end = t_start + duration
                nxt = t_start
                i = 0
                while True:
                    nxt += rng.exponential(1.0 / qps)
                    if nxt >= t_end:
                        break
                    d = nxt - time.monotonic()
                    if d > 0:
                        time.sleep(d)
                    pri = 1 if i % 4 == 0 else 0
                    i += 1
                    try:
                        futures.append(server.submit(
                            one_row(), deadline_ms=0.6 * slo_ms,
                            priority=pri))
                    except Rejected as e:
                        rejected[e.reason] = rejected.get(e.reason,
                                                          0) + 1
                    if i % 8 == 0:
                        depth_max = max(depth_max,
                                        server.health()["queue_depth"])
                submitted = i
                # -- drain: every future must resolve, each into
                # exactly one bucket
                for f in futures:
                    try:
                        f.result(timeout=120)
                        served.append((f.t_done - f.t_enq) * 1000.0)
                    except DeadlineExceeded:
                        expired += 1
                    except Rejected:
                        shed += 1        # evicted from the queue
                    except Exception as e:  # noqa: BLE001
                        if f.done():
                            other.append(repr(e)[:120])
                        else:
                            unresolved += 1
                turned_away = (sum(rejected.values()) + shed + expired)
                outcome = {
                    "mult": mult, "offered_qps": round(qps, 1),
                    "submitted": submitted, "served": len(served),
                    "rejected": rejected, "shed_evicted": shed,
                    "expired": expired, "unresolved": unresolved,
                    "other_errors": other, "depth_max": depth_max,
                    "served_p99_ms": (round(float(np.percentile(
                        served, 99)), 2) if served else None),
                }
                if turned_away > 0:
                    break               # real pressure reached
            health = server.health()
        counters = {k: obs.counter_value("serving." + k) for k in
                    ("requests", "rejected", "shed", "expired")}
    finally:
        for name in ("queue_limit", "serving_shed", "metrics"):
            flags.reset_flag(name)
        obs.set_enabled(None)

    problems = []
    accounted = (outcome["served"] + sum(outcome["rejected"].values())
                 + outcome["shed_evicted"] + outcome["expired"])
    if accounted != outcome["submitted"] or outcome["other_errors"]:
        problems.append(
            "conservation broken: submitted %d != served %d + rejected "
            "%s + shed %d + expired %d (other: %s)"
            % (outcome["submitted"], outcome["served"],
               outcome["rejected"], outcome["shed_evicted"],
               outcome["expired"], outcome["other_errors"]))
    if outcome["unresolved"]:
        problems.append("%d future(s) left unresolved"
                        % outcome["unresolved"])
    if outcome["depth_max"] > queue_limit:
        problems.append("queue depth %d exceeded the %d limit"
                        % (outcome["depth_max"], queue_limit))
    turned_away = (sum(outcome["rejected"].values())
                   + outcome["shed_evicted"] + outcome["expired"])
    if turned_away == 0:
        problems.append("no request was ever shed/rejected/expired — "
                        "the overload never pressured the gate "
                        "(offered %.0f qps)" % outcome["offered_qps"])
    if outcome["served"] == 0:
        problems.append("overload served nothing at all — shedding "
                        "must preserve goodput, not replace it")
    elif (outcome["served_p99_ms"] is not None
            and outcome["served_p99_ms"] > slo_ms):
        problems.append("admitted-request p99 %.1fms blew the %.1fms "
                        "SLO despite shedding"
                        % (outcome["served_p99_ms"], slo_ms))

    verdict = {
        "slo_ms": round(slo_ms, 2),
        "baseline_p50_ms": round(p50, 2),
        "capacity_qps": round(cap_qps, 1),
        "queue_limit": queue_limit,
        "overload": outcome,
        "health": {"healthy": health["healthy"],
                   "queue_depth": health["queue_depth"]},
        "counters": counters,
        "problems": problems,
        "ok": not problems,
    }
    print(json.dumps(verdict))
    if problems:
        sys.stderr.write("serving overload gate failed:\n  - "
                         + "\n  - ".join(problems) + "\n")
        return 1
    return 0


def probe_trace(args):
    """Request-tracing acceptance gate (--trace): under the Poisson
    sweep, every over-SLO request must have produced a KEPT trace in
    the telemetry sink whose span-sum matches the latency the driver
    measured on its own future, with the full waterfall (queue ->
    coalesce -> dispatch) and the engine-step cross-reference
    reconstructable from the sink alone; and a calm (well-under-
    capacity) run must keep ~only head-sampled traces — the tail
    sampler's whole bargain: everything when it matters, noise floor
    when it doesn't.

    Two phases on one server: "calm" at ~25% of the calibrated
    capacity, then "overload" at 2x capacity (the queue grows, requests
    blow the slow threshold, every one of them must leave a trace).
    Latencies are measured from the future's own t_enq/t_done stamps —
    the same monotonic clock the spans are cut from, so the span-sum
    comparison is exact, which is precisely the regression this gate
    pins (a dispatch that dropped the enqueue stamp would tear the two
    clocks apart)."""
    import numpy as np

    from paddle_tpu import flags
    from paddle_tpu import observability as obs

    if HERE not in sys.path:
        sys.path.insert(0, HERE)
    import trace_query

    sink_dir = args.sink_dir or tempfile.mkdtemp(prefix="serve_trace_")
    obs.set_enabled(True)
    server, one_row, info = build_server(
        args.model, int8=args.int8, calib_batches=args.calib_batches,
        buckets=args.buckets, max_wait_ms=args.max_wait_ms,
        seed=args.seed)
    rng = np.random.RandomState(args.seed)
    phases = {}
    with server:
        server.warmup(one_row())
        # calibrate unloaded latency (tracing still off: the trace
        # flags are set after, so calibration leaves no traces)
        lat = []
        for _ in range(20):
            t0 = time.monotonic()
            server.run(one_row())
            lat.append((time.monotonic() - t0) * 1000.0)
        p50 = float(np.median(lat))
        slow_ms = args.serving_slo_ms or max(25.0, 8.0 * p50)
        cap_qps = 1000.0 / max(p50, 1e-3)
        # trace_buffer must exceed the overload phase's peak queue
        # depth — an evicted in-flight trace emits nothing at finish
        flags.set_flags({"metrics": True, "trace_slow_ms": slow_ms,
                         "trace_sample": args.trace_sample,
                         "trace_buffer": 16384})
        def run_phase(phase, qps):
            sink = os.path.join(sink_dir, "trace_%s.jsonl" % phase)
            obs.reset()
            obs.attach_sink(sink)
            futs = []
            t0 = time.monotonic()
            t_end = t0 + args.duration
            nxt = t0
            while True:
                nxt += rng.exponential(1.0 / qps)
                if nxt >= t_end:
                    break
                d = nxt - time.monotonic()
                if d > 0:
                    time.sleep(d)
                futs.append(server.submit(one_row()))
            for f in futs:
                f.result(timeout=600)
            stats = obs.reqtrace.stats()
            obs.detach_sink()
            traces, _, _ = trace_query.load([sink])
            phases[phase] = {"futs": futs, "sink": sink, "qps": qps,
                             "traces": traces, "stats": stats}

        run_phase("calm", max(1.0, 0.25 * cap_qps))
        # "2x capacity" in offered load: the coalescing batcher's real
        # capacity is a batch-size multiple of the single-row rate, so
        # escalate the multiplier until the queue actually outruns the
        # slow threshold (the final escalation is the scored phase;
        # each gets its own sink so earlier attempts don't pollute it)
        for mult in (2.0, 8.0, 32.0, 128.0):
            run_phase("overload_x%g" % mult, mult * cap_qps)
            phases["overload"] = phases.pop("overload_x%g" % mult)
            over_seen = any(
                f.t_done is not None
                and (f.t_done - f.t_enq) * 1000.0 > slow_ms
                for f in phases["overload"]["futs"])
            if over_seen:
                break
    obs.set_enabled(None)

    problems = []
    # -- overload: every over-SLO request left a kept, exact,
    #    reconstructable trace
    over = phases["overload"]
    n_over = 0
    missing = []         # over-SLO but no kept trace in the sink
    mismatched = []      # kept but span-sum disagrees with the future
    incomplete = []      # kept but the waterfall is not reconstructable
    for f in over["futs"]:
        if f.t_done is None or f.trace_id is None:
            continue
        meas_ms = (f.t_done - f.t_enq) * 1000.0
        if meas_ms <= slow_ms:
            continue
        n_over += 1
        spans = over["traces"].get(f.trace_id)
        if not spans:
            missing.append(f.trace_id)
            continue
        s = trace_query.summarize(f.trace_id, spans)
        child_sum = sum(s["phases"].get(p, 0.0)
                        for p in ("queue", "coalesce", "dispatch"))
        tol = max(1.0, 0.02 * meas_ms)
        if (abs(s["total_ms"] - meas_ms) > tol
                or abs(child_sum - meas_ms) > tol):
            mismatched.append((f.trace_id, round(s["total_ms"], 3),
                               round(child_sum, 3), round(meas_ms, 3)))
            continue
        root_args = ((s["root"] or {}).get("args") or {})
        if (any(p not in s["phases"]
                for p in ("queue", "coalesce", "dispatch"))
                or root_args.get("engine_step") is None):
            incomplete.append(f.trace_id)
    if n_over == 0:
        problems.append("overload phase produced no over-SLO request "
                        "(offered %.1f qps vs slow_ms %.1f)"
                        % (over["qps"], slow_ms))
    if missing:
        problems.append("%d over-SLO request(s) left no kept trace: %s"
                        % (len(missing), missing[:5]))
    if mismatched:
        problems.append("%d trace(s) disagree with the measured "
                        "latency (id, root_ms, span_sum_ms, "
                        "measured_ms): %s"
                        % (len(mismatched), mismatched[:3]))
    if incomplete:
        problems.append("%d trace(s) missing waterfall phases or the "
                        "engine_step cross-ref: %s"
                        % (len(incomplete), incomplete[:5]))

    # -- calm: ~only head-sampled keeps (the noise floor)
    calm = phases["calm"]
    calm_n = len(calm["futs"])
    calm_keeps = {tid: trace_query.summarize(tid, sp)["keep"]
                  for tid, sp in calm["traces"].items()}
    calm_unsampled = [t for t, k in calm_keeps.items() if k != "sampled"]
    # tolerate stragglers (a GC pause can make one calm request
    # genuinely slow — that keep is the tracer doing its job)
    if len(calm_unsampled) > max(1, int(0.05 * calm_n)):
        problems.append("calm phase kept %d non-head-sampled trace(s) "
                        "of %d requests (want ~only sampled): %s"
                        % (len(calm_unsampled), calm_n,
                           sorted(set(calm_keeps.values()))))
    if args.trace_sample > 0 and calm_n >= 30 and not calm_keeps:
        problems.append("calm phase kept no traces at sample rate %g "
                        "over %d requests" % (args.trace_sample, calm_n))

    verdict = {
        "slow_ms": round(slow_ms, 2),
        "baseline_p50_ms": round(p50, 2),
        "calm": {"requests": calm_n, "kept": len(calm_keeps),
                 "kept_by": calm["stats"]["kept_by"]},
        "overload": {"requests": len(over["futs"]),
                     "over_slo": n_over,
                     "kept": len(over["traces"]),
                     "kept_by": over["stats"]["kept_by"],
                     "evicted": over["stats"]["evicted"]},
        "sink_dir": sink_dir,
        "problems": problems,
        "ok": not problems,
    }
    print("trace: " + json.dumps(verdict))
    if problems:
        sys.stderr.write("serving trace gate failed:\n  - "
                         + "\n  - ".join(problems) + "\n")
        return 1
    return 0


def slo_gate(rows, slo_ms, floor_qps):
    """Highest achieved QPS among levels meeting the p99 SLO; exit-1
    verdict when it undercuts the floor."""
    ok = [r["qps_achieved"] for r in rows
          if r["p99_ms"] is not None and r["p99_ms"] <= slo_ms]
    best = max(ok) if ok else 0.0
    return best, best >= floor_qps


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="mlp", choices=MODELS)
    ap.add_argument("--qps", default="4,8,16",
                    help="comma-separated offered QPS levels to sweep")
    ap.add_argument("--duration", type=float, default=2.0,
                    help="seconds of load per level")
    ap.add_argument("--no-int8", dest="int8", action="store_false",
                    help="serve the fp32 frozen program (skip PTQ)")
    ap.add_argument("--calib-batches", type=int, default=4)
    ap.add_argument("--buckets", default=None,
                    help="bucket edges, e.g. 1,2,4,8 (default: the "
                         "serving_buckets flag)")
    ap.add_argument("--max-wait-ms", type=float, default=None,
                    help="dispatch deadline (default: the "
                         "serving_max_wait_ms flag)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sink-dir", default=None,
                    help="directory for the per-level telemetry sinks "
                         "(default: a fresh temp dir)")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="p99 latency SLO for the CI gate")
    ap.add_argument("--slo-floor-qps", type=float, default=0.0,
                    help="exit 1 if the best QPS meeting --slo-ms is "
                         "below this")
    ap.add_argument("--serving-slo-ms", type=float, default=None,
                    help="server-side SLO fed to the burn-rate monitor "
                         "(InferenceServer slo_ms) — health() flips "
                         "unhealthy when the sweep burns its budget")
    ap.add_argument("--check-health", action="store_true",
                    help="assert the readiness probe works: healthy "
                         "before load, unhealthy (burning) under an "
                         "SLO the sweep cannot meet (default "
                         "--serving-slo-ms 0.05)")
    ap.add_argument("--autoscale", action="store_true",
                    help="elastic-fleet gate: a FleetRouter must scale "
                         "out on a load spike's fast burn window (slow "
                         "window still quiet) and p99 must recover "
                         "under the SLO with zero dropped requests")
    ap.add_argument("--fleet-max", type=int, default=3,
                    help="FleetRouter max_workers for --autoscale")
    ap.add_argument("--overload", action="store_true",
                    help="run the overload-protection gate: 4x "
                         "sustained overload with admission control "
                         "armed; asserts bounded queue, exact "
                         "served/rejected/expired conservation, and "
                         "admitted-request p99 within the SLO")
    ap.add_argument("--queue-limit", type=int, default=32,
                    help="PADDLE_TPU_QUEUE_LIMIT used by --overload "
                         "(default 32)")
    ap.add_argument("--trace", action="store_true",
                    help="request-tracing gate: every over-SLO request "
                         "under a 2x-capacity Poisson load must leave "
                         "a kept trace in the sink whose span-sum "
                         "matches the measured latency; a calm run "
                         "keeps ~only head-sampled traces")
    ap.add_argument("--trace-sample", type=float, default=0.25,
                    help="head-sample rate for the --trace gate's calm "
                         "phase")
    args = ap.parse_args(argv)
    if args.autoscale:
        return probe_autoscale(args)
    if args.overload:
        return probe_overload(args)
    if args.trace:
        return probe_trace(args)
    if args.check_health and args.serving_slo_ms is None:
        # an SLO so tight every served request violates it: the sweep
        # load IS the injected burn
        args.serving_slo_ms = 0.05

    qps_levels = [float(q) for q in args.qps.split(",") if q.strip()]
    server, one_row, info = build_server(
        args.model, int8=args.int8, calib_batches=args.calib_batches,
        buckets=args.buckets, max_wait_ms=args.max_wait_ms,
        seed=args.seed, slo_ms=args.serving_slo_ms)
    print("== %s (%s) ==" % (args.model,
                             "int8" if args.int8 else "fp32"))
    if "quantized_ops" in info:
        print("quantized %d op(s), skipped %d" % (
            info["quantized_ops"], info["skipped_ops"]))
    health_log = []
    rows = probe_serving(server, one_row, qps_levels,
                         duration=args.duration, seed=args.seed,
                         sink_dir=args.sink_dir, health_log=health_log)
    print(render_table(rows))
    summary = {"model": args.model, "int8": args.int8, "levels": rows}
    print(json.dumps(summary))
    if args.check_health:
        baseline = health_log[0] if health_log else None
        flipped = [r["qps_offered"] for r in rows
                   if r.get("health") and not r["health"]["healthy"]]
        verdict = {
            "serving_slo_ms": args.serving_slo_ms,
            "baseline_healthy": bool(baseline and baseline["healthy"]),
            "flipped_unhealthy_at_qps": flipped,
            "ok": bool(baseline and baseline["healthy"] and flipped),
        }
        print("health check: " + json.dumps(verdict))
        if not verdict["ok"]:
            sys.stderr.write(
                "serving health check failed: expected healthy() before "
                "load and an unhealthy burn under slo_ms=%s\n"
                % args.serving_slo_ms)
            return 1
    if args.slo_ms is not None:
        best, ok = slo_gate(rows, args.slo_ms, args.slo_floor_qps)
        print("slo: best qps with p99<=%.1fms: %.2f (floor %.1f)"
              % (args.slo_ms, best, args.slo_floor_qps))
        if not ok:
            sys.stderr.write(
                "serving SLO gate failed: %.2f qps under p99<=%.1fms "
                "is below the %.1f floor\n"
                % (best, args.slo_ms, args.slo_floor_qps))
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
