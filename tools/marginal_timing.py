"""Shared core of the marginal-cost timing protocol (used by bench.py's
flash bench and tools/flash_block_sweep.py — one implementation so the
sweep table and the benchmark that cites it measure the same thing).

On the tunneled chip a single dispatch carries ~1-2.5s of
session-variable overhead that dwarfs ms-scale kernels; the protocol
times a jitted ``lax.fori_loop`` of data-dependency-chained steps at two
loop counts and reports (T_hi - T_lo)/Δn, cancelling the fixed overhead.

Also a CLI: the metrics-OFF seam-overhead budget check. The telemetry
layer's whole contract is that a disabled seam costs one cached-bool
check (README quotes ~0.3 µs); ``--budget-ns`` turns that promise into
an asserting gate CI can run::

    python tools/marginal_timing.py --budget-ns 5000

measures the marginal per-call cost of the instrumented no-op seam
(``obs.inc`` + ``obs.span`` + ``obs.time_block`` with the gate down,
empty-loop baseline subtracted) and exits 1 if the best-of-rounds
exceeds the budget — a regression in the off path fails the build
instead of quietly taxing every engine step.
"""


def run_marginal_protocol(variants, args, reps, warmup_rounds=1):
    """The shared two-loop-count timing driver.

    ``variants``: {key: (fn_lo, n_lo, fn_hi, n_hi)} — jitted chained
    loops for the same computation at two loop counts. Every window is
    compiled+warmed once, then all windows are timed INTERLEAVED for
    ``reps`` rounds (so overhead drift hits every variant equally).
    ``warmup_rounds`` untimed interleaved rounds run before timing; one
    is usually enough, but a session whose allocator/tunnel state is
    still settling after the first interleaved dispatch needs a second
    (BENCH_r05 still showed a 65.5 ms first-rep spread with one).

    Returns {key: (marginal_seconds, per_rep_marginals)} where the
    headline marginal is diff-of-medians — median wall per loop count,
    then difference, so one outlier window cannot skew it — and
    ``per_rep_marginals`` are the paired per-round differences for error
    bars. Callers must treat non-positive values as overhead noise, not
    kernel signal."""
    import time

    import jax
    import numpy as np

    # Each window is tagged with a host span (no-ops unless
    # PADDLE_TPU_METRICS / a profiler session is up), so a protocol run
    # dumps straight to chrome-trace: per-variant lo/hi windows as
    # labeled slices, outlier reps visible at a glance.
    from paddle_tpu import observability as obs

    wall = {}
    for key, (fn_lo, _, fn_hi, _) in variants.items():
        with obs.span("marginal:compile", variant=key):
            jax.device_get(fn_lo(*args))    # compile + warm
            jax.device_get(fn_hi(*args))
        wall[key] = ([], [])
    # Untimed interleaved rounds before timing starts: the first
    # *interleaved* dispatch after the compile loop still eats stragglers
    # (host-side caching, allocator growth), which otherwise lands in
    # rep 0 of whichever variant runs first — observed as a 65.5ms
    # flash_attn_bwd_ms spread against a 3.4ms median.
    for wr in range(warmup_rounds):
        for key, (fn_lo, _, fn_hi, _) in variants.items():
            with obs.span("marginal:warmup", variant=key, round=wr):
                jax.device_get(fn_lo(*args))
                jax.device_get(fn_hi(*args))
    for rep in range(reps):
        for key, (fn_lo, _, fn_hi, _) in variants.items():
            for which, fn in ((0, fn_lo), (1, fn_hi)):
                with obs.span("marginal:rep", variant=key, rep=rep,
                              window="hi" if which else "lo"):
                    t0 = time.perf_counter()
                    jax.device_get(fn(*args))
                    dt = time.perf_counter() - t0
                wall[key][which].append(dt)
    out = {}
    for key, (_, n_lo, _, n_hi) in variants.items():
        lo, hi = wall[key]
        dn = n_hi - n_lo
        headline = (float(np.median(hi)) - float(np.median(lo))) / dn
        per_rep = [(h - l) / dn for l, h in zip(lo, hi)]
        out[key] = (headline, per_rep)
    return out


def measure_seam_overhead_ns(iters=200000, rounds=5):
    """Marginal per-call nanoseconds of one metrics-OFF seam: the
    engine's per-step pattern (counter inc + span ctx + time_block ctx)
    with the gate down, minus an empty-loop baseline, per iteration.
    Returns (best_ns, per_round_ns) — best-of-rounds is the asserting
    number (scheduler noise only ever inflates a round)."""
    import time

    from paddle_tpu import observability as obs

    was = obs.enabled()
    obs.set_enabled(False)
    try:
        def seam_loop(n):
            inc, span, time_block = obs.inc, obs.span, obs.time_block
            t0 = time.perf_counter_ns()
            for _ in range(n):
                inc("seam.counter")
                with span("seam"):
                    pass
                with time_block("seam.ms"):
                    pass
            return time.perf_counter_ns() - t0

        def empty_loop(n):
            t0 = time.perf_counter_ns()
            for _ in range(n):
                pass
            return time.perf_counter_ns() - t0

        seam_loop(1000)  # warm the code paths
        empty_loop(1000)
        per_round = []
        for _ in range(rounds):
            dt = seam_loop(iters) - empty_loop(iters)
            per_round.append(max(0.0, dt / iters))
    finally:
        obs.set_enabled(True if was else None)
    return min(per_round), per_round


def main(argv=None):
    import argparse
    import json
    import os
    import sys

    sys.path.insert(0, os.path.abspath(
        os.path.join(os.path.dirname(__file__), os.pardir)))
    p = argparse.ArgumentParser(
        description="metrics-off telemetry seam overhead check")
    p.add_argument("--iters", type=int, default=200000,
                   help="seam calls per timing round (default 200000)")
    p.add_argument("--rounds", type=int, default=5,
                   help="timing rounds; best-of is the headline")
    p.add_argument("--budget-ns", type=float, default=None,
                   help="fail (exit 1) if the best-of-rounds marginal "
                   "seam cost exceeds this many nanoseconds per call")
    args = p.parse_args(argv)
    best, per_round = measure_seam_overhead_ns(args.iters, args.rounds)
    out = {
        "seam_overhead_ns": round(best, 1),
        "per_round_ns": [round(r, 1) for r in per_round],
        "iters": args.iters,
    }
    if args.budget_ns is not None:
        out["budget_ns"] = args.budget_ns
        out["within_budget"] = best <= args.budget_ns
    print(json.dumps(out))
    if args.budget_ns is not None and best > args.budget_ns:
        print("FAIL: metrics-off seam overhead %.1f ns/call exceeds "
              "budget %.1f ns" % (best, args.budget_ns), file=sys.stderr)
        return 1
    return 0


def chained_grad_loop(grad_fn, n):
    """One jitted call running ``n`` fwd+bwd steps of ``grad_fn(q, k, v)
    -> (dq, dk, dv)`` chained by a data dependency: the 1e-30*dq term
    makes step i+1 depend on step i's output so XLA cannot collapse the
    loop, while perturbing q by less than one bf16 ulp."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def run(q, k, v):
        def body(_, carry):
            dq, dk, dv = grad_fn(
                q + (1e-30 * carry[0]).astype(q.dtype), k, v)
            return dq, dk, dv
        init = (jnp.zeros_like(q), jnp.zeros_like(k), jnp.zeros_like(v))
        return lax.fori_loop(0, n, body, init)
    return run


if __name__ == "__main__":
    import sys

    sys.exit(main())
