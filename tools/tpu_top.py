#!/usr/bin/env python
"""tpu_top — live one-screen summary of a streaming telemetry sink.

Tails the JSONL file a running process streams through
``PADDLE_TPU_METRICS_SINK`` (observability/export.py JsonlSink) and
renders a refreshing top-style screen: step rate and step-latency
percentiles from the "step" spans, cache hit ratio and HBM gauges from
the periodic "snap" metric snapshots, and the last nan/inf event — the
at-a-glance view of a training/serving loop without attaching a
profiler or stopping anything.

Usage:
    python tools/tpu_top.py /path/metrics.h0.jsonl            # follow
    python tools/tpu_top.py /path/metrics.h0.jsonl --once     # one shot
    python tools/tpu_top.py SINK --interval 5 --metrics-lines 20

Rotation-safe: when the live file is atomically rotated away the tail
drains the freshly rotated segment before following the new live file.
"""
import argparse
import collections
import os
import sys
import time

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), os.pardir)))

from paddle_tpu.observability.export import SinkTail  # noqa: E402,F401
from paddle_tpu.observability.health import (  # noqa: E402
    HEARTBEAT_EVENT,
    RankHealth,
)
from paddle_tpu.observability.metrics import snapshot_text  # noqa: E402

# Step spans kept for the rate/latency window.
STEP_WINDOW = 512
# Step-rate lookback (seconds of span timestamps).
RATE_WINDOW_S = 60.0


class TopState:
    """Rolling state the screen renders from."""

    def __init__(self):
        self.host = None
        self.pid = None
        self.events = 0
        self.steps = collections.deque(maxlen=STEP_WINDOW)  # (ts_us, dur)
        self.total_steps = 0
        self.last_snap = None
        self.last_snap_ts = None
        self.last_nan_inf = None
        self.ranks = {}  # host id -> RankHealth (heartbeat liveness)
        # kept request traces (reqtrace tail sampler): newest last
        self.slow_traces = collections.deque(maxlen=8)

    def consume(self, ev):
        self.events += 1
        kind = ev.get("t")
        if self.host is None and "host" in ev:
            self.host = ev["host"]
        if kind == "meta":
            self.pid = ev.get("pid", self.pid)
        elif kind == "span":
            name = ev.get("name")
            if name == "step":
                self.steps.append((ev.get("ts", 0.0), ev.get("dur", 0.0)))
                self.total_steps += 1
            elif name == "nan_inf_trip":
                self.last_nan_inf = ev
            elif name == "trace.request":
                # a kept trace's root span — the tail sampler only
                # emits these for slow/errored/head-sampled requests
                self.slow_traces.append(ev)
            elif name == HEARTBEAT_EVENT:
                host = ev.get("host", 0)
                rh = self.ranks.get(host)
                if rh is None:
                    interval = (ev.get("args") or {}).get("interval_ms")
                    rh = self.ranks[host] = RankHealth(
                        host, heartbeat_ms=interval)
                rh.observe(ev)
        elif kind == "snap":
            self.last_snap = ev.get("metrics") or {}
            self.last_snap_ts = ev.get("ts")

    # -- derived ----------------------------------------------------------
    def step_rate(self):
        if not self.steps:
            return 0.0, None, None
        newest = self.steps[-1][0]
        horizon = newest - RATE_WINDOW_S * 1e6
        recent = [(ts, dur) for ts, dur in self.steps if ts >= horizon]
        if len(recent) < 2:
            recent = list(self.steps)
        span_s = max(1e-6, (recent[-1][0] - recent[0][0]) / 1e6)
        rate = (len(recent) - 1) / span_s if len(recent) > 1 else 0.0
        durs = sorted(d / 1e3 for _, d in recent)
        p50 = durs[len(durs) // 2]
        return rate, p50, durs[-1]

    def cache_ratio(self):
        snap = self.last_snap or {}
        c = snap.get("counters") or {}
        hits = c.get("engine.cache_hit", 0)
        misses = c.get("engine.cache_miss", 0)
        total = hits + misses
        return (hits / total if total else None), hits, misses


def _fmt_bytes(n):
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return ("%d B" % n) if unit == "B" else "%.1f %s" % (n, unit)
        n /= 1024.0
    return str(n)


def render(state, path, metrics_lines=12, now_us=None):
    """One screen of text from the rolling state."""
    now_us = time.time_ns() / 1e3 if now_us is None else now_us
    lines = []
    head = "tpu_top — %s" % path
    if state.host is not None:
        head += "  host=h%s" % state.host
    if state.pid is not None:
        head += "  pid=%s" % state.pid
    head += "  events=%d" % state.events
    lines.append(head)
    lines.append("-" * min(96, max(48, len(head))))

    rate, p50, worst = state.step_rate()
    lines.append(
        "steps: %d total   rate %.2f/s   p50 %sms   max %sms"
        % (state.total_steps, rate,
           "%.2f" % p50 if p50 is not None else "-",
           "%.2f" % worst if worst is not None else "-"))
    ratio, hits, misses = state.cache_ratio()
    lines.append(
        "cache: hit ratio %s   (%d hits / %d misses)"
        % ("%.1f%%" % (ratio * 100) if ratio is not None else "-",
           hits, misses))

    gauges = (state.last_snap or {}).get("gauges") or {}
    hbm = {k: v for k, v in gauges.items() if k.startswith("hbm.")}
    if hbm:
        lines.append("hbm:   live %s (resident %s + transient %s)   "
                     "peak %s   compile-peak %s"
                     % (_fmt_bytes(hbm.get("hbm.live_bytes")),
                        _fmt_bytes(hbm.get("hbm.resident_bytes")),
                        _fmt_bytes(hbm.get("hbm.transient_bytes")),
                        _fmt_bytes(hbm.get("hbm.live_bytes_peak")),
                        _fmt_bytes(hbm.get("hbm.compile_peak_bytes"))))
        if hbm.get("hbm.device_bytes_limit"):
            in_use = hbm.get("hbm.device_bytes_in_use")
            limit = hbm.get("hbm.device_bytes_limit")
            pct = (100.0 * in_use / limit) if in_use and limit else None
            lines.append("dev:   in use %s / %s%s"
                         % (_fmt_bytes(in_use), _fmt_bytes(limit),
                            "   (%.1f%%)" % pct if pct is not None else ""))
    else:
        lines.append("hbm:   (no snapshot with hbm gauges yet)")

    frac = gauges.get("goodput.frac")
    if frac is not None:
        # live goodput bar: [#### goodput | badput] + the category the
        # badput is mostly made of (the one-line attribution answer)
        width = 40
        filled = max(0, min(width, int(round(frac * width))))
        bar = "#" * filled + "." * (width - filled)
        bad = sorted(
            ((k[len("goodput."):-len("_ms")], v)
             for k, v in gauges.items()
             if k.startswith("goodput.") and k.endswith("_ms")
             and k[len("goodput."):-len("_ms")] not in
             ("wall", "badput", "compute", "input_wait", "host_sync")
             and v > 0),
            key=lambda kv: -kv[1])
        detail = "   top badput: %s %.0fms" % bad[0] if bad else ""
        mfu = gauges.get("mfu.mfu")
        if mfu:
            detail += "   mfu %.1f%%" % (100.0 * mfu)
        lines.append("goodput: %5.1f%% [%s]%s"
                     % (100.0 * frac, bar, detail))

    hot = sorted(
        ((k[len("opprof."):-len("_ms")], v) for k, v in gauges.items()
         if k.startswith("opprof.pt.") and k.endswith("_ms") and v > 0),
        key=lambda kv: -kv[1])
    if hot:
        # hot-ops panel: the opprof.<tag>_ms gauges stop_profiler set —
        # device time per framework op, hottest first
        total_hot = sum(v for _, v in hot)
        parts = ["%s %.2fms" % (tag, v) for tag, v in hot[:4]]
        afrac = gauges.get("opprof.attributed_frac")
        lines.append(
            "hot ops: %s%s"
            % ("   ".join(parts),
               ("   (attributed %.1f%%)" % (100.0 * afrac))
               if afrac is not None else ""))
        for tag, v in hot[:6]:
            width = 28
            filled = max(1, int(round(width * v / total_hot))) \
                if total_hot else 0
            lines.append("  %-34s %8.3fms [%s]"
                         % (tag[:34], v, "#" * filled
                            + "." * (width - filled)))

    if state.last_nan_inf is not None:
        args = state.last_nan_inf.get("args") or {}
        age_s = max(0.0, (now_us - state.last_nan_inf.get("ts", now_us))
                    / 1e6)
        lines.append("nan/inf: %s %r at step %s (%d NaN / %d Inf), %.0fs "
                     "ago" % (args.get("kind", "?"), args.get("var", "?"),
                              args.get("step", "?"), args.get("nan", 0),
                              args.get("inf", 0), age_s))
    else:
        lines.append("nan/inf: none")

    if state.slow_traces:
        # slow-requests panel: the root spans of traces the tail
        # sampler KEPT — each line is the trace_query lookup key
        lines.append("slow requests (kept traces, newest last — "
                     "tools/trace_query.py --trace ID):")
        for ev in state.slow_traces:
            a = ev.get("args") or {}
            phases = [(ph, a.get(k)) for ph, k in
                      (("queue", "queue_ms"), ("coalesce", "coalesce_ms"),
                       ("exec", "exec_ms"))
                      if isinstance(a.get(k), (int, float))]
            dom = max(phases, key=lambda kv: kv[1])[0] if phases else "-"
            total = a.get("total_ms", ev.get("dur", 0.0) / 1e3)
            lines.append("  %s · %8.2f ms · %-8s %s"
                         % (a.get("trace", "?"), total, dom,
                            ("[%s]" % a["keep"]) if a.get("keep") else ""))

    if state.ranks:
        now_s = now_us / 1e6
        parts = []
        for host in sorted(state.ranks):
            rh = state.ranks[host]
            status = rh.status(now_s)
            age = (now_s - rh.last_hb_ts
                   if rh.last_hb_ts is not None else None)
            parts.append("h%s %s (step %s, hb %s ago)"
                         % (host, status.upper(),
                            rh.last_step if rh.last_step is not None else "-",
                            "%.1fs" % age if age is not None else "-"))
        lines.append("health: " + "   ".join(parts))
    else:
        lines.append("health: (no heartbeats yet — set "
                     "PADDLE_TPU_HEARTBEAT_MS)")

    if state.last_snap and metrics_lines > 0:
        lines.append("")
        lines.append("== metrics (Prometheus exposition, truncated) ==")
        text = snapshot_text(state.last_snap)
        body = [ln for ln in text.splitlines()
                if not ln.startswith("# ")]
        lines.extend(body[:metrics_lines])
        if len(body) > metrics_lines:
            lines.append("... %d more series" % (len(body) - metrics_lines))
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="live one-screen summary of a streaming telemetry "
        "sink (PADDLE_TPU_METRICS_SINK JSONL file)")
    p.add_argument("sink", help="JSONL sink file to tail")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh seconds (default 2)")
    p.add_argument("--once", action="store_true",
                   help="parse the whole file, print one screen, exit")
    p.add_argument("--metrics-lines", type=int, default=12,
                   help="metric series shown in the exposition panel")
    p.add_argument("--no-clear", action="store_true",
                   help="do not clear the terminal between refreshes")
    args = p.parse_args(argv)

    tail = SinkTail(args.sink)
    state = TopState()
    try:
        while True:
            for ev in tail.poll():
                state.consume(ev)
            screen = render(state, args.sink,
                            metrics_lines=args.metrics_lines)
            if args.once:
                print(screen)
                return 0
            if not args.no_clear:
                sys.stdout.write("\x1b[2J\x1b[H")
            print(screen)
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
