#!/usr/bin/env python
"""trace_query — reconstruct request traces from telemetry sinks.

Loads one or more JSONL sink files (or directories of them — rotation
segments and host-tagged per-rank files included), groups the
``trace.*`` spans the request tracer (observability/reqtrace) kept by
trace ID, and renders:

* ``--slowest N``   a table of the slowest kept traces with per-phase
                    self-time (queue / coalesce / dispatch / ...), keep
                    reason, and dominant phase;
* ``--trace ID``    one trace's waterfall — each span as an offset +
                    duration bar, batch fan-in members listed, and the
                    device segment cross-referenced: the engine "step"
                    span matching the dispatch's ``engine_step`` plus
                    the hottest per-op device-time gauges
                    (``opprof.pt.*``) from the last metrics snapshot;
* ``--exemplar M``  the trace ID attached to metric ``M``'s exemplar
                    slot (bucket-max observation) in the last snapshot,
                    then that trace's waterfall — the SLO-page -> trace
                    round trip.

Everything is reconstructed FROM THE SINKS ALONE — the same files a
fleet run ships — so the tool works post-mortem on any collected dump.

Usage::

    python tools/trace_query.py /tmp/run/metrics.jsonl --slowest 10
    python tools/trace_query.py /tmp/run --merge --trace 4b5ad68fd6369c83
    python tools/trace_query.py sink.jsonl --exemplar serving.request_ms
    python tools/trace_query.py sink.jsonl --slowest 5 --json
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from paddle_tpu.observability.export import (  # noqa: E402
    iter_events,
    sink_file_set,
)

# phases rendered in causal order when present (anything else appends
# in timestamp order)
PHASE_ORDER = ("request", "route", "queue", "coalesce", "dispatch",
               "restart", "train_start", "resume", "rollback",
               "step_enqueue", "step_retire")


def expand_paths(paths, merge=False):
    """Sink args -> concrete file list. Directories expand to every
    ``*.jsonl`` inside; ``--merge`` additionally globs each file arg's
    whole family (``base*`` — host-tagged per-rank files and rotation
    segments of a multi-process run)."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sink_file_set(p))
            continue
        if merge:
            base = p
            for ext in (".jsonl", ".json"):
                if base.endswith(ext):
                    base = base[: -len(ext)]
                    break
            fam = sorted(glob.glob(base + "*"))
            for f in fam:
                files.extend(sink_file_set(f))
        else:
            files.extend(sink_file_set(p))
    # preserve order, drop duplicates (family globs overlap rotations)
    seen, out = set(), []
    for f in files:
        if f not in seen:
            seen.add(f)
            out.append(f)
    return out


def load(files):
    """-> (traces, engine_spans, last_snap) where traces is
    {trace_id: [span event dict, ...]} for every ``trace.*`` span,
    engine_spans is {step: event} for the host "step" spans (device
    cross-ref), and last_snap is the final metrics snapshot seen."""
    traces = {}
    engine_spans = {}
    last_snap = None
    for path in files:
        for ev in iter_events(path):
            t = ev.get("t")
            if t == "snap":
                last_snap = ev.get("metrics") or last_snap
                continue
            if t != "span":
                continue
            name = str(ev.get("name", ""))
            args = ev.get("args") or {}
            if name.startswith("trace."):
                tid = args.get("trace")
                if tid:
                    traces.setdefault(tid, []).append(ev)
            elif name == "step" and args.get("step") is not None:
                try:
                    engine_spans[int(args["step"])] = ev
                except (TypeError, ValueError):
                    pass
    return traces, engine_spans, last_snap


def phase_of(ev):
    return str(ev.get("name", ""))[len("trace."):]


def summarize(tid, spans):
    """One trace -> {id, total_ms, keep, phases: {phase: self_ms},
    dominant, root, t0_us, t1_us, incarnations}."""
    root = None
    phases = {}
    t0 = t1 = None
    incarnations = set()
    for ev in spans:
        ph = phase_of(ev)
        ts = float(ev.get("ts") or 0.0)
        dur = float(ev.get("dur") or 0.0)
        t0 = ts if t0 is None else min(t0, ts)
        t1 = ts + dur if t1 is None else max(t1, ts + dur)
        args = ev.get("args") or {}
        if "incarnation" in args:
            incarnations.add(args["incarnation"])
        if ph == "request" and (root is None
                                or dur > float(root.get("dur") or 0.0)):
            root = ev
            continue  # the root's wall overlaps every child; not self-time
        phases[ph] = phases.get(ph, 0.0) + dur / 1e3
    if root is not None:
        total_ms = float(root.get("dur") or 0.0) / 1e3
        keep = (root.get("args") or {}).get("keep")
    else:
        total_ms = ((t1 - t0) / 1e3) if t0 is not None else 0.0
        keep = next(((ev.get("args") or {}).get("keep") for ev in spans
                     if (ev.get("args") or {}).get("keep")), None)
    dominant = max(phases.items(), key=lambda kv: kv[1])[0] \
        if phases else None
    return {"id": tid, "total_ms": total_ms, "keep": keep,
            "phases": phases, "dominant": dominant, "root": root,
            "t0_us": t0, "t1_us": t1,
            "incarnations": sorted(incarnations)}


def _phase_key(ev):
    ph = phase_of(ev)
    rank = PHASE_ORDER.index(ph) if ph in PHASE_ORDER else len(PHASE_ORDER)
    return (float(ev.get("ts") or 0.0), rank)


def render_waterfall(tid, spans, engine_spans=None, snap=None, width=36):
    """Text waterfall: one line per span, offset + duration + a bar
    positioned inside the trace's wall. The dispatch span's device
    segment is cross-referenced via its ``engine_step`` arg."""
    s = summarize(tid, spans)
    t0 = s["t0_us"] or 0.0
    span_wall = max(1e-9, (s["t1_us"] or t0) - t0)
    lines = ["trace %s  total %.3f ms  keep=%s%s" % (
        tid, s["total_ms"], s["keep"],
        ("  incarnations=%s" % s["incarnations"]
         if s["incarnations"] else ""))]
    engine_step = None
    for ev in sorted(spans, key=_phase_key):
        ph = phase_of(ev)
        ts = float(ev.get("ts") or 0.0)
        dur = float(ev.get("dur") or 0.0)
        args = dict(ev.get("args") or {})
        if ph == "dispatch" and args.get("engine_step") is not None:
            engine_step = args.get("engine_step")
        off = max(0, int(round((ts - t0) / span_wall * width)))
        w = max(1 if dur > 0 else 0,
                int(round(dur / span_wall * width)))
        w = min(w, width - min(off, width - 1))
        bar = " " * min(off, width - 1) + ("#" * w if w else "|")
        bar = bar[:width].ljust(width)
        extras = []
        for k in ("rows", "bucket", "worker", "members", "step",
                  "engine_step", "kind", "attempt", "incarnation",
                  "restored_step", "error"):
            if k in args:
                v = args[k]
                if k == "members" and isinstance(v, list):
                    v = ",".join(str(m)[:8] for m in v)
                extras.append("%s=%s" % (k, v))
        lines.append("  %-13s +%9.3fms %9.3fms [%s] %s" % (
            ph, (ts - t0) / 1e3, dur / 1e3, bar,
            " ".join(extras)))
    if engine_step is not None and engine_spans:
        dev = engine_spans.get(int(engine_step))
        if dev is not None:
            ts = float(dev.get("ts") or 0.0)
            dur = float(dev.get("dur") or 0.0)
            lines.append("  %-13s +%9.3fms %9.3fms (engine step %s)"
                         % ("device:step", (ts - t0) / 1e3, dur / 1e3,
                            engine_step))
    if snap:
        hot = sorted(
            ((k[len("opprof."):], v)
             for k, v in (snap.get("gauges") or {}).items()
             if k.startswith("opprof.pt.") and k.endswith("_ms")
             and isinstance(v, (int, float)) and v > 0),
            key=lambda kv: -kv[1])[:3]
        if hot:
            lines.append("  device ops:   " + "   ".join(
                "%s %.3fms" % (tag, v) for tag, v in hot))
    return "\n".join(lines)


def render_slowest(traces, n):
    rows = sorted((summarize(t, sp) for t, sp in traces.items()),
                  key=lambda r: -r["total_ms"])[:n]
    out = ["%-18s %10s %-9s %-9s %s" % (
        "trace", "total ms", "keep", "dominant", "per-phase self ms")]
    for r in rows:
        detail = "  ".join("%s %.3f" % (ph, ms) for ph, ms in sorted(
            r["phases"].items(), key=lambda kv: -kv[1]))
        out.append("%-18s %10.3f %-9s %-9s %s" % (
            r["id"], r["total_ms"], r["keep"] or "-",
            r["dominant"] or "-", detail))
    return "\n".join(out), rows


def exemplar_lookup(snap, metric):
    """-> (trace_id, value) from the last snapshot's exemplar slots, or
    (None, None)."""
    ex = (snap or {}).get("exemplars") or {}
    e = ex.get(metric)
    if not e:
        return None, None
    return e.get("trace_id"), e.get("value")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("sinks", nargs="+",
                    help="JSONL sink files or directories")
    ap.add_argument("--merge", action="store_true",
                    help="also load each sink's whole file family "
                         "(host-tagged per-rank files + rotation "
                         "segments: base*)")
    ap.add_argument("--slowest", type=int, default=None, metavar="N",
                    help="table of the N slowest kept traces")
    ap.add_argument("--trace", default=None, metavar="ID",
                    help="render one trace's waterfall")
    ap.add_argument("--exemplar", default=None, metavar="METRIC",
                    help="look up METRIC's exemplar trace in the last "
                         "snapshot and render it")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    files = expand_paths(args.sinks, merge=args.merge)
    if not files:
        sys.stderr.write("trace_query: no sink files found\n")
        return 1
    traces, engine_spans, snap = load(files)
    if not any((args.slowest, args.trace, args.exemplar)):
        args.slowest = 10

    if args.trace is not None:
        spans = traces.get(args.trace)
        if not spans:
            sys.stderr.write("trace_query: trace %r not found in %d "
                             "kept trace(s)\n" % (args.trace, len(traces)))
            return 1
        if args.json:
            print(json.dumps(summarize(args.trace, spans),
                             default=str))
        else:
            print(render_waterfall(args.trace, spans, engine_spans, snap))
        return 0

    if args.exemplar is not None:
        tid, value = exemplar_lookup(snap, args.exemplar)
        if tid is None:
            sys.stderr.write("trace_query: metric %r carries no "
                             "exemplar in the last snapshot\n"
                             % args.exemplar)
            return 1
        spans = traces.get(tid)
        if args.json:
            out = {"metric": args.exemplar, "value": value, "trace": tid,
                   "found": bool(spans)}
            if spans:
                out["summary"] = summarize(tid, spans)
            print(json.dumps(out, default=str))
        else:
            print("exemplar of %s = %s -> trace %s"
                  % (args.exemplar, value, tid))
            if spans:
                print(render_waterfall(tid, spans, engine_spans, snap))
            else:
                print("(trace %s was not kept in these sinks)" % tid)
        return 0 if spans else 1

    table, rows = render_slowest(traces, args.slowest)
    if args.json:
        print(json.dumps([{k: v for k, v in r.items() if k != "root"}
                          for r in rows], default=str))
    else:
        print("%d kept trace(s) in %d file(s)" % (len(traces), len(files)))
        print(table)
    return 0


if __name__ == "__main__":
    sys.exit(main())
