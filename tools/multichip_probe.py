#!/usr/bin/env python
"""Multi-chip scaling probe: train the same model over a dp mesh at 1/2/4/8
(forced host) devices and report the weak-scaling efficiency curve.

Each device count runs in its OWN subprocess with ``JAX_PLATFORMS=cpu`` and
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` — XLA fixes the
device count at backend init, so a single process cannot sweep it. The
child trains through the real mesh path (``Executor.run(mesh=...)`` →
engine GSPMD jit, the exact seam bench.py and production use) with a
weak-scaling batch (``--batch-per-device × N``) and publishes its
throughput as ``probe.samples_per_sec``/``probe.devices`` gauges into a
per-run telemetry sink (observability JsonlSink); the parent assembles the
scaling table FROM THE SINKS — the same files a fleet run would ship — so
the probe doubles as an end-to-end test of the telemetry export path.

Efficiency here is CAPACITY-normalized: eff(N) = tput(N) / tput(1). The
N forced-host devices all share one physical CPU, so the real-hardware
definition tput(N)/(N×tput(1)) could never exceed ~1/N no matter how
good the graph is — whereas against flat capacity, healthy weak scaling
(same total FLOPs/sec, partitioning overhead only) sits near 1.0 and a
broken graph (state gathered to host every step, per-count recompiles,
unsharded fallbacks) craters well below it. bench.py's real-device
path uses the per-device normalization; this probe is the
shared-capacity stand-in. ``--efficiency-floor F`` exits non-zero when
the largest-N efficiency lands below F — the CI guard for "the psum
path stopped scaling".

``--predict`` additionally turns on the engine's SPMD prediction seam
(PADDLE_TPU_SPMD_PREDICT) in every child: the first run of each mesh
executable parses its own jitted HLO and emits a
``spmd.prediction_delta`` span into the sink; the parent prints the
predicted-vs-measured collective counts/bytes and per-device peak next
to the scaling table. ``--predict-tolerance F`` makes it a CI gate:
exit non-zero when any device count's psum count mismatches or its
collective bytes miss by more than the relative tolerance.

``--zero1`` flips every child onto the ZeRO-1 sharded weight update
(PADDLE_TPU_ZERO=1; optionally ``--bucket-mb N`` for bucketed gradient
reduction) so two invocations give the replicated-vs-sharded scaling
A/B that bench.py's multichip section automates.

Usage:
  python tools/multichip_probe.py --model mlp --devices 1,2,4,8
  python tools/multichip_probe.py --model bert --efficiency-floor 0.6
  python tools/multichip_probe.py --predict --predict-tolerance 0.1
  python tools/multichip_probe.py --model mlp --zero1 --bucket-mb 4
Bench integration: ``PADDLE_TPU_BENCH=multichip python bench.py`` calls
``probe_scaling()`` when fewer than 2 real devices exist.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

# tiny CPU-sized geometries: the probe measures the partitioner's scaling
# behavior, not the chip, so the models only need enough compute per step
# to dominate python dispatch
MODELS = ("mlp", "bert", "resnet50")


def _build(model, batch):
    """(main, startup, loss_var, feed_dict, param_rule_hints) on tiny
    CPU geometry. Import inside: the child must set platform env before
    jax loads."""
    import numpy as np

    from paddle_tpu import models

    rng = np.random.RandomState(0)
    if model == "mlp":
        main, startup, h = models.mnist.get_model(lr=0.01)
        feed = {"img": rng.randn(batch, 784).astype(np.float32),
                "label": rng.randint(0, 10, (batch, 1)).astype(np.int64)}
        return main, startup, h["loss"], feed
    if model == "bert":
        kw = dict(d_model=64, n_layers=2, n_heads=2, d_inner=128)
        main, startup, h = models.bert.get_model(
            batch_size=batch, seq_len=32, vocab_size=512, dropout=0.0,
            lr=1e-4, max_position=512, **kw)
        feed = models.bert.make_fake_batch(batch, 32, 512, kw["n_heads"])
        return main, startup, h["loss"], feed
    if model == "resnet50":
        # cifar resnet at depth 20: the real conv/BN/residual training
        # graph without imagenet-sized CPU step times
        main, startup, h = models.resnet.get_model(
            dataset="cifar10", depth=20, class_num=10, lr=0.1)
        feed = {"img": rng.randn(batch, 3, 32, 32).astype(np.float32),
                "label": rng.randint(0, 10, (batch, 1)).astype(np.int64)}
        return main, startup, h["loss"], feed
    raise ValueError("unknown model %r (want one of %s)" % (model, MODELS))


def _child(model, batch_per_device, steps, warmup):
    """Runs inside the forced-device-count subprocess: train over a dp
    mesh spanning every (virtual) device, publish throughput gauges to
    the attached sink, print one JSON line as a sink-less fallback."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import observability as obs
    from paddle_tpu.parallel import ShardingRules, make_mesh

    n = len(jax.devices())
    batch = batch_per_device * n
    main, startup, loss, feed = _build(model, batch)
    mesh = make_mesh({"dp": n})
    exe = fluid.Executor()
    scope = fluid.Scope()
    feed = {k: jax.device_put(v) for k, v in feed.items()}
    with fluid.scope_guard(scope):
        exe.run(startup)
        run = lambda: exe.run(main, feed=feed, fetch_list=[loss],
                              mesh=mesh, shard_rules=ShardingRules(),
                              return_numpy=False)[0]
        out = None
        for _ in range(warmup):
            out = run()
        jax.device_get(out)  # drain compile + warmup before timing
        t0 = time.perf_counter()
        for _ in range(steps):
            out = run()
        val = jax.device_get(out)  # drain the dispatched pipeline
        elapsed = time.perf_counter() - t0
    assert np.isfinite(float(np.asarray(val).reshape(-1)[0]))
    tput = batch * steps / elapsed
    obs.set_gauge("probe.samples_per_sec", tput)
    obs.set_gauge("probe.devices", n)
    obs.set_gauge("probe.batch", batch)
    obs.detach_sink()  # final snapshot + flush (attach came from the flag)
    print(json.dumps({"devices": n, "samples_per_sec": tput,
                      "batch": batch}))


def _read_sink_gauges(path):
    """Last metrics snapshot's gauges from a JSONL sink file (the child's
    detach_sink() emits one on exit)."""
    gauges = None
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("t") == "snap":
                    gauges = (ev.get("metrics") or {}).get("gauges") or gauges
    except OSError:
        return None
    return gauges


def _read_sink_span(path, name):
    """Last "span" event named ``name`` from a JSONL sink file; returns
    its args dict (or None). The prediction seam emits exactly one
    ``spmd.prediction_delta`` per compiled executable."""
    args = None
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("t") == "span" and ev.get("name") == name:
                    args = ev.get("args") or args
    except OSError:
        return None
    return args


def probe_scaling(model="mlp", devices=(1, 2, 4, 8), batch_per_device=64,
                  steps=12, warmup=3, sink_dir=None, predict=False,
                  zero1=False, bucket_mb=0.0):
    """Run the sweep; returns {n: samples_per_sec} (plus
    {n: prediction_delta args} when ``predict``). Parent-side only.
    ``zero1``/``bucket_mb`` turn on the ZeRO-1 sharded weight update
    (PADDLE_TPU_ZERO) and bucketed gradient reduction
    (PADDLE_TPU_GRAD_BUCKET_MB) in every child — the A/B lever bench.py
    sweeps to price the sharded update against the replicated one."""
    results = {}
    predictions = {}
    own_tmp = sink_dir is None
    if own_tmp:
        sink_dir = tempfile.mkdtemp(prefix="multichip_probe_")
    for n in devices:
        sink = os.path.join(sink_dir, "probe_dp%d%s.jsonl"
                            % (n, "_zero1" if zero1 else ""))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=%d"
                            % n).strip()
        env["PADDLE_TPU_METRICS"] = "1"
        env["PADDLE_TPU_METRICS_SINK"] = sink
        if zero1:
            env["PADDLE_TPU_ZERO"] = "1"
            if bucket_mb:
                env["PADDLE_TPU_GRAD_BUCKET_MB"] = str(bucket_mb)
        if predict:
            env["PADDLE_TPU_SPMD_PREDICT"] = "1"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        cmd = [sys.executable, os.path.abspath(__file__), "--child",
               "--model", model, "--batch-per-device",
               str(batch_per_device), "--steps", str(steps), "--warmup",
               str(warmup)]
        r = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                           text=True)
        if r.returncode != 0:
            sys.stderr.write(r.stderr[-2000:] + "\n")
            raise RuntimeError("probe child (dp=%d) failed rc=%d"
                               % (n, r.returncode))
        gauges = _read_sink_gauges(sink)
        if gauges and "probe.samples_per_sec" in gauges:
            results[n] = float(gauges["probe.samples_per_sec"])
        else:  # sink missing/rotated away — fall back to the stdout line
            last = [l for l in r.stdout.splitlines() if l.strip()][-1]
            results[n] = float(json.loads(last)["samples_per_sec"])
        if predict:
            delta = _read_sink_span(sink, "spmd.prediction_delta")
            if delta is not None:
                predictions[n] = delta
    if predict:
        return results, predictions
    return results


def efficiency_table(results):
    """[(n, tput, efficiency)] with efficiency = tput(n)/tput(1) — the
    shared-capacity normalization (see module docstring): the N virtual
    devices split one CPU, so flat throughput IS perfect weak scaling."""
    base = results.get(1)
    rows = []
    for n in sorted(results):
        t = results[n]
        eff = (t / base) if base else None
        rows.append((n, t, eff))
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="mlp", choices=MODELS)
    ap.add_argument("--devices", default="1,2,4,8",
                    help="comma-separated device counts to sweep")
    ap.add_argument("--batch-per-device", type=int, default=64)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--efficiency-floor", type=float, default=0.0,
                    help="exit 1 if the largest-N efficiency is below this")
    ap.add_argument("--predict", action="store_true",
                    help="enable the engine's SPMD prediction seam in "
                         "every child and print predicted-vs-measured "
                         "collective counts/bytes and per-device peak "
                         "next to the scaling table")
    ap.add_argument("--predict-tolerance", type=float, default=None,
                    metavar="F",
                    help="CI gate for --predict: exit 1 when any device "
                         "count's psum count mismatches or collective "
                         "bytes miss by more than this relative "
                         "tolerance (e.g. 0.1)")
    ap.add_argument("--sink-dir", default=None,
                    help="directory for the per-run telemetry sinks "
                         "(default: a fresh temp dir)")
    ap.add_argument("--zero1", action="store_true",
                    help="train with the ZeRO-1 sharded weight update "
                         "(PADDLE_TPU_ZERO=1 in every child) — combine "
                         "with a plain run for the replicated-vs-"
                         "sharded A/B")
    ap.add_argument("--bucket-mb", type=float, default=0.0, metavar="MB",
                    help="with --zero1: bucketed gradient reduction "
                         "size (PADDLE_TPU_GRAD_BUCKET_MB)")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        _child(args.model, args.batch_per_device, args.steps, args.warmup)
        return 0

    devices = tuple(int(d) for d in args.devices.split(","))
    predict = args.predict or args.predict_tolerance is not None
    predictions = {}
    if predict:
        results, predictions = probe_scaling(
            args.model, devices, args.batch_per_device, args.steps,
            args.warmup, args.sink_dir, predict=True,
            zero1=args.zero1, bucket_mb=args.bucket_mb)
    else:
        results = probe_scaling(args.model, devices,
                                args.batch_per_device, args.steps,
                                args.warmup, args.sink_dir,
                                zero1=args.zero1,
                                bucket_mb=args.bucket_mb)
    rows = efficiency_table(results)
    mode = ("zero1 bucket=%gMB" % args.bucket_mb if args.zero1
            and args.bucket_mb else
            "zero1" if args.zero1 else "replicated")
    print("update: %s" % mode)
    print("%-8s %-18s %s" % ("devices", "samples/sec", "efficiency"))
    for n, t, eff in rows:
        print("%-8d %-18.2f %s" % (n, t,
                                   "%.3f" % eff if eff is not None else "-"))
    summary = {"model": args.model, "update": mode,
               "throughput": {str(n): round(t, 2) for n, t, _ in rows},
               "efficiency": {str(n): round(eff, 4)
                              for n, _, eff in rows if eff is not None}}
    print(json.dumps(summary))
    rc = 0
    if predict:
        print("\n%-8s %-16s %-26s %-8s %s"
              % ("devices", "psums p/m", "coll bytes p/m", "ratio",
                 "peak bytes p/m"))
        for n in sorted(results):
            d = predictions.get(n)
            if d is None:  # dp=1: no collectives, no seam event
                print("%-8d %-16s %-26s %-8s %s" % (n, "-", "-", "-", "-"))
                continue
            bp, bm = d["bytes_predicted"], d["bytes_measured"]
            ratio = (bm / bp) if bp else float("nan")
            print("%-8d %-16s %-26s %-8s %s" % (
                n,
                "%d/%d" % (d["psums_predicted"], d["psums_measured"]),
                "%d/%d" % (bp, bm), "%.3f" % ratio,
                "%d/%d" % (d["peak_bytes_predicted"],
                           d["peak_bytes_measured"])))
            if args.predict_tolerance is not None:
                if d["psums_predicted"] != d["psums_measured"]:
                    sys.stderr.write(
                        "predict gate: psum count %d != measured %d at "
                        "%d devices\n" % (d["psums_predicted"],
                                          d["psums_measured"], n))
                    rc = 1
                if bp and abs(ratio - 1.0) > args.predict_tolerance:
                    sys.stderr.write(
                        "predict gate: collective bytes off by %.1f%% "
                        "(> %.1f%%) at %d devices\n"
                        % (abs(ratio - 1.0) * 100,
                           args.predict_tolerance * 100, n))
                    rc = 1
        if args.predict_tolerance is not None and not predictions:
            sys.stderr.write("predict gate: no spmd.prediction_delta "
                             "events found in any child sink\n")
            rc = 1
    if rows and rows[-1][2] is not None \
            and rows[-1][2] < args.efficiency_floor:
        sys.stderr.write(
            "scaling efficiency %.3f at %d devices below floor %.3f\n"
            % (rows[-1][2], rows[-1][0], args.efficiency_floor))
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
