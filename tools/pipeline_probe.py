#!/usr/bin/env python
"""Async-dispatch depth probe: sweep ``dispatch_steps`` over the same
training loop and report steps/sec per depth, the depth-N speedup over
the synchronous loop, and whether every depth's loss trajectory is
BIT-EXACT with depth 1 — the windowed engine's core promise (the window
reorders WHEN results are read, never WHAT was computed: the rng path is
`(seed, run_counter)` derived inside the jitted step, so the schedule is
identical at every depth).

Each depth runs a fresh Executor + Scope (resetting the engine's run
counter, so parameter init and the step sequence replay identically) and
drives the dispatch-overhead-scale MLP step: depth 1 materializes every
step's loss before the next dispatch (the synchronous engine's loop);
depth N hands back DeferredFetch placeholders and pays ONE drain per
timed window. ``reps`` timed windows per depth, median published — the
step is milliseconds-scale, so single windows swing with scheduler
noise.

Methodology note for CPU-probe runs (the usual CI box): the win depth
removes is the per-step host materialization, which on a local CPU
device is ~tens of µs — so healthy speedups sit at a few percent here,
versus the ~100 ms-per-step round trips a tunneled TPU hides. The
``--floor`` gate therefore defaults just under 1.0 (no-REGRESSION, with
room for scheduler noise), not to a speedup target; bench.py's pipeline
block carries the headline ratios.

Usage:
  JAX_PLATFORMS=cpu python tools/pipeline_probe.py
  python tools/pipeline_probe.py --depths 1,2,4,8,16 --floor 1.0
Exit status: 1 when the largest depth's steps/sec lands below
``--floor × depth-1 steps/sec`` or any depth's losses diverge from
depth 1 (unless --skip-parity).
"""

import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def probe_depths(depths=(1, 2, 4, 8), steps=40, warmup=6, reps=5,
                 batch=512):
    """{depth: (steps_per_sec, [loss bytes in step order])}. Every depth
    replays the identical schedule (fresh engine, same feeds), so the
    k-th captured loss must match bit-for-bit across depths.

    The timed windows are INTERLEAVED round-robin across depths (rep 0
    of every depth, then rep 1, ...) and the median per depth is
    published: on a shared CPU box the same config swings ~2x with
    scheduler load drift, and sequential per-depth timing folds that
    drift into the depth ratio — interleaving makes every depth sample
    the same load profile (the flash bench's protocol)."""
    import jax
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu import models

    rng = np.random.RandomState(0)
    x = rng.randn(batch, 784).astype(np.float32)
    y = rng.randint(0, 10, (batch, 1)).astype(np.int64)
    runs = {}

    def make_window(exe, scope, main, feed, loss, d):
        """One timed window: ``steps`` dispatches + the drain, run under
        this depth's own scope (each depth owns its state)."""
        def window():
            with fluid.scope_guard(scope):
                t0 = time.perf_counter()
                vals = [exe.run(main, feed=feed, fetch_list=[loss],
                                dispatch_steps=d)[0]
                        for _ in range(steps)]
                exe.sync()  # drain inside the timed window
                wall = time.perf_counter() - t0
            # placeholders are all resolved after sync(); reading them
            # here costs no device round trip
            return wall, [np.asarray(v).tobytes() for v in vals]
        return window

    for d in depths:
        main, startup, h = models.mnist.get_model(lr=0.01)
        exe = fluid.Executor()
        scope = fluid.Scope()
        feed = {"img": jax.device_put(x), "label": jax.device_put(y)}
        with fluid.scope_guard(scope):
            exe.run(startup)
            for _ in range(warmup):  # compile + warm the windowed path
                exe.run(main, feed=feed, fetch_list=[h["loss"]],
                        dispatch_steps=d)
            exe.sync()
        runs[d] = {"window": make_window(exe, scope, main, feed,
                                         h["loss"], d),
                   "walls": [], "losses": []}
    for _ in range(reps):
        for d in depths:
            r = runs[d]
            wall, losses = r["window"]()
            r["walls"].append(wall)
            r["losses"].extend(losses)
    return {d: (steps / float(np.median(r["walls"])), r["losses"])
            for d, r in runs.items()}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--depths", default="1,2,4,8",
                    help="comma-separated dispatch_steps values; depth 1 "
                         "is the baseline and is added if missing")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--warmup", type=int, default=6)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--floor", type=float, default=0.95,
                    help="exit 1 if largest-depth steps/sec < floor x "
                         "depth-1 steps/sec (default leaves CPU "
                         "scheduler-noise headroom; use 1.0 on hardware)")
    ap.add_argument("--skip-parity", action="store_true",
                    help="skip the bit-exact loss comparison")
    args = ap.parse_args(argv)

    depths = sorted({1} | {int(d) for d in args.depths.split(",")})
    results = probe_depths(tuple(depths), args.steps, args.warmup,
                           args.reps, args.batch)
    base_tput, base_losses = results[1]
    print("%-8s %-14s %-9s %s" % ("depth", "steps/sec", "speedup",
                                  "parity_vs_depth1"))
    parity_ok = True
    summary = {"throughput": {}, "speedup": {}, "parity": {}}
    for d in depths:
        tput, losses = results[d]
        same = losses == base_losses
        parity_ok = parity_ok and same
        label = ("baseline" if d == 1 else
                 "bit-exact" if same else "MISMATCH")
        print("%-8d %-14.2f %-9.3f %s" % (d, tput, tput / base_tput,
                                          label))
        summary["throughput"][str(d)] = round(tput, 2)
        summary["speedup"][str(d)] = round(tput / base_tput, 4)
        summary["parity"][str(d)] = label
    print(json.dumps(summary))
    rc = 0
    top = depths[-1]
    if results[top][0] < args.floor * base_tput:
        sys.stderr.write(
            "depth-%d throughput %.2f below floor %.2f (%.2f x %.2f "
            "steps/sec at depth 1)\n"
            % (top, results[top][0], args.floor * base_tput, args.floor,
               base_tput))
        rc = 1
    if not args.skip_parity and not parity_ok:
        sys.stderr.write("loss trajectory diverged from depth 1 — the "
                         "dispatch window changed the computation\n")
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
