"""Weight-decay regularizers appended as grad-modifying ops
(reference: python/paddle/fluid/regularizer.py)."""

from paddle_tpu.layer_helper import LayerHelper

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l2_decay", block=block)
        decay = helper.create_variable_for_type_inference(dtype=param.dtype)
        block.append_op(
            type="scale",
            inputs={"X": [param]},
            outputs={"Out": [decay]},
            attrs={"scale": self._coeff},
        )
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l1_decay", block=block)
        sign = helper.create_variable_for_type_inference(dtype=param.dtype)
        block.append_op(
            type="sign", inputs={"X": [param]}, outputs={"Out": [sign]}
        )
        decay = helper.create_variable_for_type_inference(dtype=param.dtype)
        block.append_op(
            type="scale",
            inputs={"X": [sign]},
            outputs={"Out": [decay]},
            attrs={"scale": self._coeff},
        )
        return decay


def append_regularization_ops(parameters_and_grads, regularization=None):
    """Add decay terms into gradients
    (reference: regularizer.py append_regularization_ops)."""
    out = []
    for param, grad in parameters_and_grads:
        if grad is None:
            out.append((param, grad))
            continue
        regularizer = getattr(param, "regularizer", None) or regularization
        if regularizer is None:
            out.append((param, grad))
            continue
        block = grad.block
        decay = regularizer(param, grad, block)
        helper = LayerHelper("regularized_grad", block=block)
        new_grad = helper.create_variable_for_type_inference(dtype=param.dtype)
        block.append_op(
            type="sum",
            inputs={"X": [grad, decay]},
            outputs={"Out": [new_grad]},
        )
        out.append((param, new_grad))
    return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
