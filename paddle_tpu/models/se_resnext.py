"""SE-ResNeXt (reference: benchmark/fluid/models/se_resnext.py and
tests/unittests/test_parallel_executor_seresnext.py SE_ResNeXt50Small)."""

import paddle_tpu.fluid as fluid


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_train=True):
    conv = fluid.layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv, act=act,
                                   is_test=not is_train)


def squeeze_excitation(input, num_channels, reduction_ratio):
    pool = fluid.layers.pool2d(input=input, pool_type="avg",
                               global_pooling=True)
    squeeze = fluid.layers.fc(input=pool,
                              size=max(num_channels // reduction_ratio, 1),
                              act="relu")
    excitation = fluid.layers.fc(input=squeeze, size=num_channels,
                                 act="sigmoid")
    excitation = fluid.layers.reshape(excitation,
                                      shape=[-1, num_channels, 1, 1])
    return fluid.layers.elementwise_mul(input, excitation)


def shortcut(input, ch_in, ch_out, stride, is_train=True):
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, is_train=is_train)
    return input


def bottleneck_block(input, ch_in, num_filters, stride, cardinality,
                     reduction_ratio, is_train=True):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu",
                          is_train=is_train)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act="relu", is_train=is_train)
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None,
                          is_train=is_train)
    scaled = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = shortcut(input, ch_in, num_filters * 2, stride,
                     is_train=is_train)
    return fluid.layers.relu(fluid.layers.elementwise_add(scaled, short))


def se_resnext(input, depth=50, cardinality=32, reduction_ratio=16,
               is_train=True, small=False):
    if small:
        # the test-suite "small" variant: one stage, few blocks, cheap input
        conv = conv_bn_layer(input, 16, 3, stride=2, act="relu",
                             is_train=is_train)
        ch_in = 16
        block_cfg = [(16, 2, 1)]
        cardinality = 8
    else:
        conv = conv_bn_layer(input, 64, 7, stride=2, act="relu",
                             is_train=is_train)
        conv = fluid.layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                                   pool_padding=1, pool_type="max")
        ch_in = 64
        depth_cfg = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3],
                     152: [3, 8, 36, 3]}[depth]
        block_cfg = [
            (128 * (2 ** i), n, 1 if i == 0 else 2)
            for i, n in enumerate(depth_cfg)
        ]
    h = conv
    for num_filters, count, stride in block_cfg:
        for j in range(count):
            h = bottleneck_block(h, ch_in, num_filters,
                                 stride if j == 0 else 1,
                                 cardinality, reduction_ratio, is_train)
            ch_in = num_filters * 2
    pool = fluid.layers.pool2d(input=h, pool_type="avg", global_pooling=True)
    return pool


def get_model(class_num=1000, image_shape=(3, 224, 224), lr=0.01,
              is_train=True, small=False):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=list(image_shape),
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        feat = se_resnext(img, is_train=is_train, small=small)
        drop = fluid.layers.dropout(x=feat, dropout_prob=0.2,
                                    is_test=not is_train)
        logits = fluid.layers.fc(input=drop, size=class_num, act=None)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=label))
        acc = fluid.layers.accuracy(
            input=fluid.layers.softmax(logits), label=label)
        if is_train:
            opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
            opt.minimize(loss)
    return main, startup, {"img": img, "label": label, "loss": loss,
                           "acc": acc, "logits": logits}
