"""MobileNet-V1 (reference: the fork's INT8 headline model,
python/paddle/fluid/contrib/int8_inference/README.md; architecture per
depthwise-separable conv stack)."""

import paddle_tpu.fluid as fluid


def conv_bn(input, num_filters, filter_size, stride=1, padding=0, groups=1,
            depthwise=False, is_train=True):
    layer = (fluid.layers.depthwise_conv2d if depthwise
             else fluid.layers.conv2d)
    conv = layer(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=padding,
        **({"groups": groups} if not depthwise else {}),
        act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv, act="relu",
                                   is_test=not is_train)


def depthwise_separable(input, ch_in, ch_out, stride, scale=1.0,
                        is_train=True):
    dw = conv_bn(input, int(ch_in * scale), 3, stride=stride, padding=1,
                 depthwise=True, is_train=is_train)
    return conv_bn(dw, int(ch_out * scale), 1, is_train=is_train)


def mobilenet_v1(input, scale=1.0, is_train=True):
    h = conv_bn(input, int(32 * scale), 3, stride=2, padding=1,
                is_train=is_train)
    cfg = [
        (32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
        (256, 256, 1), (256, 512, 2),
        (512, 512, 1), (512, 512, 1), (512, 512, 1), (512, 512, 1),
        (512, 512, 1),
        (512, 1024, 2), (1024, 1024, 1),
    ]
    for ch_in, ch_out, stride in cfg:
        h = depthwise_separable(h, ch_in, ch_out, stride, scale, is_train)
    return fluid.layers.pool2d(input=h, pool_type="avg", global_pooling=True)


def get_model(class_num=1000, image_shape=(3, 224, 224), scale=1.0, lr=0.01,
              is_train=True):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=list(image_shape),
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        feat = mobilenet_v1(img, scale=scale, is_train=is_train)
        logits = fluid.layers.fc(input=feat, size=class_num, act=None)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=label))
        acc = fluid.layers.accuracy(
            input=fluid.layers.softmax(logits), label=label)
        if is_train:
            opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
            opt.minimize(loss)
    return main, startup, {"img": img, "label": label, "loss": loss,
                           "acc": acc, "logits": logits}
