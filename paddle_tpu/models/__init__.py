"""Model zoo: the reference's benchmark + book model families, built on the
``paddle_tpu.fluid`` layer API (reference: benchmark/fluid/models/
{mnist,resnet,vgg,se_resnext,stacked_dynamic_lstm,machine_translation}.py and
python/paddle/fluid/tests/book/).

Every builder returns ``(feeds, loss, extras)``-style handles so the same
model drops into Executor.run, CompiledProgram.with_data_parallel, or the
bench harness.
"""

from paddle_tpu.models import mnist  # noqa: F401
from paddle_tpu.models import resnet  # noqa: F401
from paddle_tpu.models import vgg  # noqa: F401
from paddle_tpu.models import se_resnext  # noqa: F401
from paddle_tpu.models import mobilenet  # noqa: F401
from paddle_tpu.models import lstm  # noqa: F401
from paddle_tpu.models import transformer  # noqa: F401
from paddle_tpu.models import bert  # noqa: F401
from paddle_tpu.models import deepfm  # noqa: F401
from paddle_tpu.models import word2vec  # noqa: F401
