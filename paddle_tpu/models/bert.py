"""BERT encoder with masked-LM + next-sentence heads (capability target per
SURVEY.md §6 north-star configs; the reference's closest artifact is the
inference-side analyzer_bert_tester.cc). Built from the same MHA/FFN blocks
as the transformer model."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.models.transformer import (
    multi_head_attention, ffn, pre_post_process,
)


def bert_encoder(src_ids, pos_ids, sent_ids, seq_lens, vocab_size,
                 max_position=512, type_vocab_size=2, d_model=768,
                 n_layers=12, n_heads=12, d_inner=3072, dropout=0.1,
                 is_train=True, use_fused_attention=True):
    word = fluid.layers.embedding(
        input=src_ids, size=[vocab_size, d_model],
        param_attr=fluid.ParamAttr(name="word_embedding"))
    pos = fluid.layers.embedding(
        input=pos_ids, size=[max_position, d_model],
        param_attr=fluid.ParamAttr(name="pos_embedding"))
    sent = fluid.layers.embedding(
        input=sent_ids, size=[type_vocab_size, d_model],
        param_attr=fluid.ParamAttr(name="sent_embedding"))
    emb = fluid.layers.elementwise_add(
        fluid.layers.elementwise_add(word, pos), sent)
    emb = fluid.layers.layer_norm(emb, begin_norm_axis=2)
    if dropout > 0:
        emb = fluid.layers.dropout(
            emb, dropout_prob=dropout, is_test=not is_train,
            dropout_implementation="upscale_in_train")

    h = emb
    for _ in range(n_layers):
        attn = multi_head_attention(
            h, h, h, d_model, n_heads, dropout, seq_lens=seq_lens,
            is_train=is_train, use_fused_attention=use_fused_attention)
        h = pre_post_process(h, attn, dropout, is_train)
        f = ffn(h, d_model, d_inner, is_train, act="gelu")
        h = pre_post_process(h, f, dropout, is_train)
    return h


def pretrain_heads(enc_out, mask_label, mask_weight, ns_label, vocab_size,
                   d_model, is_train=True):
    """Masked-LM over the full sequence (weighted by the mask) + NSP on
    position 0 — the padding/ragged-free formulation XLA wants."""
    # MLM
    mlm_h = fluid.layers.fc(input=enc_out, size=d_model, num_flatten_dims=2,
                            act="gelu")
    mlm_h = fluid.layers.layer_norm(mlm_h, begin_norm_axis=2)
    mlm_logits = fluid.layers.fc(input=mlm_h, size=vocab_size,
                                 num_flatten_dims=2)
    flat_logits = fluid.layers.reshape(mlm_logits, shape=[-1, vocab_size])
    flat_label = fluid.layers.reshape(mask_label, shape=[-1, 1])
    mlm_loss = fluid.layers.softmax_with_cross_entropy(
        logits=flat_logits, label=flat_label)
    flat_w = fluid.layers.reshape(mask_weight, shape=[-1, 1])
    weighted = fluid.layers.elementwise_mul(mlm_loss, flat_w)
    denom = fluid.layers.elementwise_add(
        fluid.layers.reduce_sum(flat_w),
        fluid.layers.fill_constant(shape=[1], dtype="float32", value=1e-6))
    mlm_mean = fluid.layers.elementwise_div(
        fluid.layers.reduce_sum(weighted), denom)

    # NSP from the [CLS] position
    first = fluid.layers.slice(enc_out, axes=[1], starts=[0], ends=[1])
    pooled = fluid.layers.fc(
        input=fluid.layers.reshape(first, shape=[-1, enc_out.shape[2]]),
        size=enc_out.shape[2], act="tanh")
    ns_logits = fluid.layers.fc(input=pooled, size=2)
    ns_loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=ns_logits, label=ns_label))

    total = fluid.layers.elementwise_add(mlm_mean, ns_loss)
    return total, mlm_mean, ns_loss


def get_model(batch_size=8, seq_len=128, vocab_size=30522, d_model=768,
              n_layers=12, n_heads=12, d_inner=3072, dropout=0.1, lr=1e-4,
              is_train=True, max_position=512, use_fused_attention=True):
    """BERT pre-training program. ``bert_base`` defaults; shrink the dims for
    tests."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(name="src_ids", shape=[seq_len],
                                dtype="int64")
        pos = fluid.layers.data(name="pos_ids", shape=[seq_len],
                                dtype="int64")
        sent = fluid.layers.data(name="sent_ids", shape=[seq_len],
                                 dtype="int64")
        seq_lens = fluid.layers.data(name="seq_lens", shape=[1],
                                     dtype="int64")
        mask_label = fluid.layers.data(name="mask_label", shape=[seq_len],
                                       dtype="int64")
        mask_weight = fluid.layers.data(name="mask_weight", shape=[seq_len],
                                        dtype="float32")
        ns_label = fluid.layers.data(name="ns_label", shape=[1],
                                     dtype="int64")
        enc = bert_encoder(src, pos, sent, seq_lens, vocab_size,
                           max_position=max_position, d_model=d_model,
                           n_layers=n_layers, n_heads=n_heads,
                           d_inner=d_inner, dropout=dropout,
                           is_train=is_train,
                           use_fused_attention=use_fused_attention)
        loss, mlm_loss, ns_loss = pretrain_heads(
            enc, mask_label, mask_weight, ns_label, vocab_size, d_model,
            is_train=is_train)
        if is_train:
            fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    feeds = {"src_ids": src, "pos_ids": pos, "sent_ids": sent,
             "seq_lens": seq_lens, "mask_label": mask_label,
             "mask_weight": mask_weight, "ns_label": ns_label}
    return main, startup, {"feeds": feeds, "loss": loss,
                           "mlm_loss": mlm_loss, "ns_loss": ns_loss,
                           "enc_out": enc}


def make_fake_batch(batch_size, seq_len, vocab_size, n_heads=None,
                    mask_frac=0.15, rng=None, varlen=False):
    """``varlen=True`` draws ragged lengths to exercise the key-padding
    masks (otherwise full-length, the bench configuration)."""
    rng = rng or np.random.RandomState(0)
    src = rng.randint(0, vocab_size, (batch_size, seq_len)).astype(np.int64)
    pos = np.tile(np.arange(seq_len, dtype=np.int64), (batch_size, 1))
    sent = np.zeros((batch_size, seq_len), np.int64)
    if varlen:
        lens = rng.randint(max(seq_len // 2, 1), seq_len + 1,
                           (batch_size, 1)).astype(np.int64)
    else:
        lens = np.full((batch_size, 1), seq_len, np.int64)
    mask_label = src.copy()
    mask_weight = (rng.rand(batch_size, seq_len) < mask_frac).astype(
        np.float32)
    ns_label = rng.randint(0, 2, (batch_size, 1)).astype(np.int64)
    return {"src_ids": src, "pos_ids": pos, "sent_ids": sent,
            "seq_lens": lens, "mask_label": mask_label,
            "mask_weight": mask_weight, "ns_label": ns_label}
