"""ResNet for CIFAR-10 and ImageNet (reference:
benchmark/fluid/models/resnet.py — resnet_cifar10:108 / resnet_imagenet:89,
get_model:171)."""

import paddle_tpu.fluid as fluid


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_train=True):
    conv = fluid.layers.conv2d(
        input=input, num_filters=ch_out, filter_size=filter_size,
        stride=stride, padding=padding, act=None, bias_attr=False)
    return fluid.layers.batch_norm(input=conv, act=act, is_test=not is_train)


def shortcut(input, ch_in, ch_out, stride, is_train=True):
    # derive the true input width from the tensor, like the reference
    # (benchmark/fluid/models/resnet.py:112 shortcut) — the bookkeeping
    # ch_in is wrong for bottleneck loop blocks (input is ch_out*4 wide),
    # and a spurious projection conv on every identity shortcut both
    # deviates from ResNet-50 and costs ~12 extra conv+BN pairs
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             is_train=is_train)
    return input


def basicblock(input, ch_in, ch_out, stride, is_train=True):
    s = shortcut(input, ch_in, ch_out, stride, is_train)
    c1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_train=is_train)
    c2 = conv_bn_layer(c1, ch_out, 3, 1, 1, act=None, is_train=is_train)
    return fluid.layers.relu(fluid.layers.elementwise_add(c2, s))


def bottleneck(input, ch_in, ch_out, stride, is_train=True):
    s = shortcut(input, ch_in, ch_out * 4, stride, is_train)
    c1 = conv_bn_layer(input, ch_out, 1, 1, 0, is_train=is_train)
    c2 = conv_bn_layer(c1, ch_out, 3, stride, 1, is_train=is_train)
    c3 = conv_bn_layer(c2, ch_out * 4, 1, 1, 0, act=None, is_train=is_train)
    return fluid.layers.relu(fluid.layers.elementwise_add(c3, s))


def layer_warp(block_func, input, ch_in, ch_out, count, stride,
               is_train=True):
    res = block_func(input, ch_in, ch_out, stride, is_train)
    for _ in range(1, count):
        res = block_func(res, ch_out, ch_out, 1, is_train)
    return res


def resnet_cifar10(input, depth=32, is_train=True):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, 16, 3, 1, 1, is_train=is_train)
    r1 = layer_warp(basicblock, conv1, 16, 16, n, 1, is_train)
    r2 = layer_warp(basicblock, r1, 16, 32, n, 2, is_train)
    r3 = layer_warp(basicblock, r2, 32, 64, n, 2, is_train)
    pool = fluid.layers.pool2d(input=r3, pool_size=8, pool_type="avg",
                               global_pooling=True)
    return pool


def resnet_imagenet(input, depth=50, is_train=True):
    cfg = {
        18: ([2, 2, 2, 1], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
    }
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(input, 64, 7, 2, 3, is_train=is_train)
    pool1 = fluid.layers.pool2d(input=conv1, pool_size=3, pool_stride=2,
                                pool_padding=1, pool_type="max")
    expansion = 4 if block_func is bottleneck else 1
    res = pool1
    ch_in = 64
    for i, count in enumerate(stages):
        ch_out = 64 * (2 ** i)
        stride = 1 if i == 0 else 2
        res = layer_warp(block_func, res, ch_in, ch_out, count, stride,
                         is_train)
        ch_in = ch_out * expansion
    pool2 = fluid.layers.pool2d(input=res, pool_size=7, pool_type="avg",
                                global_pooling=True)
    return pool2


def get_model(batch_size=32, dataset="cifar10", depth=None, class_num=None,
              lr=0.01, is_train=True):
    """(reference: benchmark/fluid/models/resnet.py:171 get_model)."""
    if dataset == "cifar10":
        shape, builder = [3, 32, 32], resnet_cifar10
        depth = depth or 32
        class_num = class_num or 10
    else:
        shape, builder = [3, 224, 224], resnet_imagenet
        depth = depth or 50
        class_num = class_num or 1000

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=shape, dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        feat = builder(img, depth=depth, is_train=is_train)
        logits = fluid.layers.fc(input=feat, size=class_num, act=None)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=label))
        acc = fluid.layers.accuracy(
            input=fluid.layers.softmax(logits), label=label)
        if is_train:
            opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
            opt.minimize(loss)
    return main, startup, {"img": img, "label": label, "loss": loss,
                           "acc": acc, "logits": logits}
