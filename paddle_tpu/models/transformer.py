"""Transformer for NMT (reference: benchmark/fluid/models/machine_translation.py
and tests/unittests/dist_transformer.py). Encoder-decoder with multi-head
attention; training is teacher-forced over padded batches with masks — the
TPU-native stand-in for the reference's LoDTensor padding-free batching
(SURVEY.md §5 long-sequence story).

The attention core emits the ``fused_attention`` op (Pallas
flash-attention kernels on TPU, XLA composition elsewhere —
paddle_tpu/kernels/flash_attention.py): padding is expressed as
per-sequence lengths, causality as a static flag, and attention-weight
dropout runs inside the kernel. A dense additive-mask path remains for
masks that aren't (length, causal)-representable.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.initializer import NumpyArrayInitializer
from paddle_tpu.layers.nn import (
    attention_bias_from_lens as _attention_bias_from_lens,
    fused_attention as _fused_attention_layer,
)


def positional_encoding_table(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype(np.float64)
    i = np.arange(d_model)[None, :].astype(np.float64)
    angle = pos / np.power(10000.0, 2 * (i // 2) / d_model)
    table = np.zeros((max_len, d_model), dtype=np.float32)
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table


def multi_head_attention(q_in, k_in, v_in, d_model, n_heads, dropout_rate,
                         mask=None, seq_lens=None, causal=False,
                         is_train=True, name=None,
                         sequence_parallel=False, sp_axis="sp",
                         use_fused_attention=True):
    """Scaled dot-product attention with head split/merge
    (reference: dist_transformer.py multi_head_attention).

    With ``mask=None`` the core is a single ``fused_attention`` op
    (Pallas flash kernels on TPU): key padding via ``seq_lens``, causal
    via the flag, attention dropout in-kernel. A dense additive ``mask``
    forces the unfused composition. ``sequence_parallel=True`` shards the
    sequence axis over the mesh's ``sp_axis`` and runs exact ring
    attention (parallel/ring_attention.py) — the long-context path; it
    requires dropout 0 and no seq_lens/mask.

    ``use_fused_attention=False`` emits the reference-style unfused
    composition (matmul→[+mask]→softmax→[dropout]→matmul) with seq_lens
    expressed as the additive bias from
    ``layers.nn.attention_bias_from_lens`` — the form the
    ``fuse-attention`` transform pass (analysis/transforms.py) rewrites
    back to the fused op at PADDLE_TPU_OPT_LEVEL>=1. Causal attention has
    no unfused emission and stays on the fused op regardless."""
    d_head = d_model // n_heads
    q = fluid.layers.fc(input=q_in, size=d_model, num_flatten_dims=2,
                        bias_attr=False)
    k = fluid.layers.fc(input=k_in, size=d_model, num_flatten_dims=2,
                        bias_attr=False)
    v = fluid.layers.fc(input=v_in, size=d_model, num_flatten_dims=2,
                        bias_attr=False)

    def split_heads(x):
        x = fluid.layers.reshape(x, shape=[0, 0, n_heads, d_head])
        return fluid.layers.transpose(x, perm=[0, 2, 1, 3])  # [B,H,T,dh]

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    if sequence_parallel:
        if mask is not None:
            raise ValueError(
                "sequence_parallel attention takes no dense mask")
        if seq_lens is not None:
            raise ValueError(
                "sequence_parallel attention does not support seq_lens; "
                "pad to full length")
        if is_train and dropout_rate > 0:
            raise ValueError(
                "sequence_parallel attention does not support attention "
                "dropout; set dropout_rate=0")
        ctx = _fused_attention_layer(
            q, k, v, causal=causal, scale=d_head ** -0.5,
            dropout_rate=0.0, sequence_parallel=True, sp_axis=sp_axis)
    elif mask is None and (use_fused_attention or causal):
        ctx = _fused_attention_layer(
            q, k, v, causal=causal, scale=d_head ** -0.5,
            seq_lens=seq_lens,
            dropout_rate=dropout_rate if is_train else 0.0)
    else:
        if mask is None and seq_lens is not None:
            mask = _attention_bias_from_lens(seq_lens, k.shape[2])
        scores = fluid.layers.matmul(q, k, transpose_y=True,
                                     alpha=d_head ** -0.5)
        if mask is not None:
            scores = fluid.layers.elementwise_add(scores, mask)
        weights = fluid.layers.softmax(scores)
        if dropout_rate > 0:
            weights = fluid.layers.dropout(
                weights, dropout_prob=dropout_rate, is_test=not is_train,
                dropout_implementation="upscale_in_train")
        ctx = fluid.layers.matmul(weights, v)  # [B,H,T,dh]
    ctx = fluid.layers.transpose(ctx, perm=[0, 2, 1, 3])
    ctx = fluid.layers.reshape(ctx, shape=[0, 0, d_model])
    return fluid.layers.fc(input=ctx, size=d_model, num_flatten_dims=2,
                           bias_attr=False)


def ffn(x, d_model, d_inner, is_train=True, act="relu"):
    h = fluid.layers.fc(input=x, size=d_inner, num_flatten_dims=2, act=act)
    return fluid.layers.fc(input=h, size=d_model, num_flatten_dims=2)


def pre_post_process(prev, out, dropout_rate, is_train):
    """residual + dropout + layer_norm (post-process 'dan')."""
    if dropout_rate > 0:
        out = fluid.layers.dropout(
            out, dropout_prob=dropout_rate, is_test=not is_train,
            dropout_implementation="upscale_in_train")
    if prev is not None:
        out = fluid.layers.elementwise_add(out, prev)
    return fluid.layers.layer_norm(out, begin_norm_axis=2)


def encoder_layer(x, d_model, n_heads, d_inner, dropout, src_lens, is_train,
                  use_fused_attention=True):
    attn = multi_head_attention(x, x, x, d_model, n_heads, dropout,
                                seq_lens=src_lens, is_train=is_train,
                                use_fused_attention=use_fused_attention)
    x = pre_post_process(x, attn, dropout, is_train)
    f = ffn(x, d_model, d_inner, is_train)
    return pre_post_process(x, f, dropout, is_train)


def decoder_layer(x, enc_out, d_model, n_heads, d_inner, dropout,
                  trg_lens, src_lens, is_train, use_fused_attention=True):
    self_attn = multi_head_attention(x, x, x, d_model, n_heads, dropout,
                                     seq_lens=trg_lens, causal=True,
                                     is_train=is_train)
    x = pre_post_process(x, self_attn, dropout, is_train)
    cross = multi_head_attention(x, enc_out, enc_out, d_model, n_heads,
                                 dropout, seq_lens=src_lens,
                                 is_train=is_train,
                                 use_fused_attention=use_fused_attention)
    x = pre_post_process(x, cross, dropout, is_train)
    f = ffn(x, d_model, d_inner, is_train)
    return pre_post_process(x, f, dropout, is_train)


def embed(ids, vocab_size, d_model, max_len, pos_ids, scope_name):
    word = fluid.layers.embedding(
        input=ids, size=[vocab_size, d_model],
        param_attr=fluid.ParamAttr(name=scope_name + "_word_emb"))
    pos_table = positional_encoding_table(max_len, d_model)
    pos = fluid.layers.embedding(
        input=pos_ids, size=[max_len, d_model],
        param_attr=fluid.ParamAttr(
            name=scope_name + "_pos_emb",
            initializer=NumpyArrayInitializer(pos_table),
            trainable=False))
    scaled = fluid.layers.scale(word, scale=float(d_model ** 0.5))
    return fluid.layers.elementwise_add(scaled, pos)


def build_transformer(src_ids, src_pos, trg_ids, trg_pos, label,
                      src_lens, trg_lens,
                      vocab_size, d_model=256, n_heads=8, d_inner=1024,
                      n_layers=4, dropout=0.1, max_len=256, is_train=True,
                      label_smooth_eps=0.1, use_fused_attention=True):
    enc = embed(src_ids, vocab_size, d_model, max_len, src_pos, "src")
    for _ in range(n_layers):
        enc = encoder_layer(enc, d_model, n_heads, d_inner, dropout,
                            src_lens, is_train,
                            use_fused_attention=use_fused_attention)

    dec = embed(trg_ids, vocab_size, d_model, max_len, trg_pos, "trg")
    for _ in range(n_layers):
        dec = decoder_layer(dec, enc, d_model, n_heads, d_inner, dropout,
                            trg_lens, src_lens, is_train,
                            use_fused_attention=use_fused_attention)

    logits = fluid.layers.fc(input=dec, size=vocab_size, num_flatten_dims=2,
                             act=None)
    flat_logits = fluid.layers.reshape(logits, shape=[-1, vocab_size])
    flat_label = fluid.layers.reshape(label, shape=[-1, 1])
    if label_smooth_eps > 0 and is_train:
        soft = fluid.layers.label_smooth(
            fluid.layers.one_hot(flat_label, depth=vocab_size),
            epsilon=label_smooth_eps)
        loss = fluid.layers.softmax_with_cross_entropy(
            logits=flat_logits, label=soft, soft_label=True)
    else:
        loss = fluid.layers.softmax_with_cross_entropy(
            logits=flat_logits, label=flat_label)
    avg_loss = fluid.layers.mean(loss)
    return avg_loss, logits


def get_model(batch_size=8, seq_len=16, vocab_size=1000, d_model=64,
              n_heads=4, d_inner=128, n_layers=2, dropout=0.1, lr=1e-3,
              is_train=True, label_smooth_eps=0.1,
              use_fused_attention=True):
    """Feeds: src/trg token ids + position ids + per-sequence valid
    lengths (key-padding masks, TPU-first: no dense [B,H,T,T] mask
    tensors; the decoder's causal mask is structural)."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(name="src", shape=[seq_len], dtype="int64")
        src_pos = fluid.layers.data(name="src_pos", shape=[seq_len],
                                    dtype="int64")
        trg = fluid.layers.data(name="trg", shape=[seq_len], dtype="int64")
        trg_pos = fluid.layers.data(name="trg_pos", shape=[seq_len],
                                    dtype="int64")
        label = fluid.layers.data(name="label", shape=[seq_len],
                                  dtype="int64")
        src_lens = fluid.layers.data(name="src_lens", shape=[1],
                                     dtype="int64")
        trg_lens = fluid.layers.data(name="trg_lens", shape=[1],
                                     dtype="int64")
        loss, logits = build_transformer(
            src, src_pos, trg, trg_pos, label, src_lens, trg_lens,
            vocab_size, d_model, n_heads, d_inner, n_layers,
            dropout, max_len=max(seq_len, 256), is_train=is_train,
            label_smooth_eps=label_smooth_eps,
            use_fused_attention=use_fused_attention)
        if is_train:
            fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    feeds = {"src": src, "src_pos": src_pos, "trg": trg, "trg_pos": trg_pos,
             "label": label, "src_lens": src_lens, "trg_lens": trg_lens}
    return main, startup, {"feeds": feeds, "loss": loss, "logits": logits}


def make_fake_batch(batch_size, seq_len, vocab_size, n_heads=None, rng=None,
                    varlen=False):
    """Synthetic copy-task batch: target = source shifted (learnable).
    ``varlen=True`` draws ragged lengths to exercise the padding masks."""
    rng = rng or np.random.RandomState(0)
    src = rng.randint(1, vocab_size, (batch_size, seq_len)).astype(np.int64)
    trg = np.concatenate(
        [np.ones((batch_size, 1), np.int64), src[:, :-1]], axis=1)
    label = src.copy()
    pos = np.tile(np.arange(seq_len, dtype=np.int64), (batch_size, 1))
    if varlen:
        lens = rng.randint(max(seq_len // 2, 1), seq_len + 1,
                           (batch_size, 1)).astype(np.int64)
    else:
        lens = np.full((batch_size, 1), seq_len, np.int64)
    return {
        "src": src, "src_pos": pos, "trg": trg, "trg_pos": pos,
        "label": label, "src_lens": lens, "trg_lens": lens.copy(),
    }
