"""MNIST models (reference: benchmark/fluid/models/mnist.py and
tests/book/test_recognize_digits.py)."""

import paddle_tpu.fluid as fluid


def mlp(img, label, hidden=(128, 64)):
    h = img
    for size in hidden:
        h = fluid.layers.fc(input=h, size=size, act="relu")
    logits = fluid.layers.fc(input=h, size=10, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits=logits, label=label))
    acc = fluid.layers.accuracy(
        input=fluid.layers.softmax(logits), label=label)
    return loss, acc, logits


def conv_net(img, label):
    """LeNet-style conv net (reference: benchmark/fluid/models/mnist.py
    cnn_model)."""
    c1 = fluid.layers.conv2d(input=img, num_filters=20, filter_size=5,
                             act="relu")
    p1 = fluid.layers.pool2d(input=c1, pool_size=2, pool_stride=2,
                             pool_type="max")
    c2 = fluid.layers.conv2d(input=p1, num_filters=50, filter_size=5,
                             act="relu")
    p2 = fluid.layers.pool2d(input=c2, pool_size=2, pool_stride=2,
                             pool_type="max")
    logits = fluid.layers.fc(input=p2, size=10, act=None)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits=logits, label=label))
    acc = fluid.layers.accuracy(
        input=fluid.layers.softmax(logits), label=label)
    return loss, acc, logits


def get_model(batch_size=64, use_conv=False, lr=0.01):
    """Build main/startup programs for an MNIST classifier."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        if use_conv:
            img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                    dtype="float32")
        else:
            img = fluid.layers.data(name="img", shape=[784], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        loss, acc, logits = (conv_net if use_conv else mlp)(img, label)
        opt = fluid.optimizer.Adam(learning_rate=lr)
        opt.minimize(loss)
    return main, startup, {"img": img, "label": label, "loss": loss,
                           "acc": acc, "logits": logits}
