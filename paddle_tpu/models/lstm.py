"""Stacked LSTM sentiment classifier (reference:
benchmark/fluid/models/stacked_dynamic_lstm.py). The reference time-steps
via dynamic LoD LSTM ops; TPU-native the recurrence is a StaticRNN →
lax.scan over padded, time-major sequences with masking."""

import paddle_tpu.fluid as fluid


def lstm_layer(x_tbd, hidden_size, is_train=True):
    """One LSTM layer over a time-major [T, B, D] tensor via StaticRNN."""
    h0 = fluid.layers.fill_constant_batch_size_like(
        input=x_tbd, shape=[-1, hidden_size], dtype="float32", value=0.0,
        input_dim_idx=1, output_dim_idx=0)
    c0 = fluid.layers.fill_constant_batch_size_like(
        input=x_tbd, shape=[-1, hidden_size], dtype="float32", value=0.0,
        input_dim_idx=1, output_dim_idx=0)
    rnn = fluid.StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x_tbd)
        h_prev = rnn.memory(init=h0)
        c_prev = rnn.memory(init=c0)
        gates = fluid.layers.fc(input=xt, size=4 * hidden_size,
                                bias_attr=True)
        gates = fluid.layers.elementwise_add(
            gates, fluid.layers.fc(input=h_prev, size=4 * hidden_size,
                                   bias_attr=False))
        i, f, g, o = fluid.layers.split(gates, num_or_sections=4, dim=1)
        i = fluid.layers.sigmoid(i)
        f = fluid.layers.sigmoid(f)
        g = fluid.layers.tanh(g)
        o = fluid.layers.sigmoid(o)
        c = fluid.layers.elementwise_add(
            fluid.layers.elementwise_mul(f, c_prev),
            fluid.layers.elementwise_mul(i, g))
        h = fluid.layers.elementwise_mul(o, fluid.layers.tanh(c))
        rnn.update_memory(h_prev, h)
        rnn.update_memory(c_prev, c)
        rnn.step_output(h)
    return rnn()


def stacked_lstm_net(seq_ids, label, dict_dim, emb_dim=64, hidden_dim=64,
                     stacked_num=2, class_num=2, is_train=True):
    """seq_ids: [B, T] int64 token ids (padded)."""
    emb = fluid.layers.embedding(input=seq_ids, size=[dict_dim, emb_dim])
    # [B, T, D] -> time-major [T, B, D]
    x = fluid.layers.transpose(emb, perm=[1, 0, 2])
    h = x
    for _ in range(stacked_num):
        h = lstm_layer(h, hidden_dim, is_train=is_train)
    # last-step hidden state: [T, B, H] -> [B, H]
    T = h.shape[0]
    last = fluid.layers.slice(h, axes=[0], starts=[T - 1], ends=[T])
    last = fluid.layers.reshape(last, shape=[-1, hidden_dim])
    logits = fluid.layers.fc(input=last, size=class_num, act=None)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=label))
    acc = fluid.layers.accuracy(input=fluid.layers.softmax(logits),
                                label=label)
    return loss, acc, logits


def get_model(batch_size=16, seq_len=32, dict_dim=5000, emb_dim=64,
              hidden_dim=64, stacked_num=2, lr=0.01, is_train=True):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        seq = fluid.layers.data(name="seq", shape=[seq_len], dtype="int64")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        loss, acc, logits = stacked_lstm_net(
            seq, label, dict_dim, emb_dim, hidden_dim, stacked_num,
            is_train=is_train)
        if is_train:
            fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, {"seq": seq, "label": label, "loss": loss,
                           "acc": acc, "logits": logits}
