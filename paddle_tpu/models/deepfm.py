"""DeepFM CTR model (reference: the dist-training CTR configs,
tests/unittests/dist_ctr.py + distributed sharded-embedding capability per
SURVEY.md §2.12). Sparse feature ids → shared embeddings feeding an FM
second-order term and a DNN tower."""

import numpy as np

import paddle_tpu.fluid as fluid


def deepfm(feat_ids, label, num_features, num_fields, embed_dim=8,
           dnn_hidden=(64, 32), is_train=True, is_distributed=False):
    """feat_ids: [B, num_fields] int64 global feature ids."""
    # first-order weights: embedding with dim 1
    w1 = fluid.layers.embedding(
        input=feat_ids, size=[num_features, 1], is_sparse=True,
        is_distributed=is_distributed,
        param_attr=fluid.ParamAttr(name="fm_w1"))
    first_order = fluid.layers.reduce_sum(
        fluid.layers.reshape(w1, shape=[-1, num_fields]), dim=1,
        keep_dim=True)

    # second-order: 0.5 * ((sum_i v_i)^2 - sum_i v_i^2)
    emb = fluid.layers.embedding(
        input=feat_ids, size=[num_features, embed_dim], is_sparse=True,
        is_distributed=is_distributed,
        param_attr=fluid.ParamAttr(name="fm_v"))  # [B, F, K]
    sum_v = fluid.layers.reduce_sum(emb, dim=1)              # [B, K]
    sum_v_sq = fluid.layers.elementwise_mul(sum_v, sum_v)
    v_sq = fluid.layers.elementwise_mul(emb, emb)
    sq_sum = fluid.layers.reduce_sum(v_sq, dim=1)
    second_order = fluid.layers.scale(
        fluid.layers.reduce_sum(
            fluid.layers.elementwise_sub(sum_v_sq, sq_sum), dim=1,
            keep_dim=True),
        scale=0.5)

    # DNN tower on flattened embeddings
    dnn = fluid.layers.reshape(emb, shape=[-1, num_fields * embed_dim])
    for size in dnn_hidden:
        dnn = fluid.layers.fc(input=dnn, size=size, act="relu")
    dnn_out = fluid.layers.fc(input=dnn, size=1, act=None)

    logit = fluid.layers.elementwise_add(
        fluid.layers.elementwise_add(first_order, second_order), dnn_out)
    loss = fluid.layers.mean(
        fluid.layers.sigmoid_cross_entropy_with_logits(
            x=logit, label=fluid.layers.cast(label, "float32")))
    pred = fluid.layers.sigmoid(logit)
    return loss, pred, logit


def get_model(batch_size=32, num_features=10000, num_fields=10, embed_dim=8,
              lr=0.01, is_train=True, is_distributed=False):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        feat = fluid.layers.data(name="feat_ids", shape=[num_fields],
                                 dtype="int64")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        loss, pred, logit = deepfm(feat, label, num_features, num_fields,
                                   embed_dim, is_train=is_train,
                                   is_distributed=is_distributed)
        if is_train:
            fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, {"feat_ids": feat, "label": label, "loss": loss,
                           "pred": pred}


def make_fake_batch(batch_size, num_features, num_fields, rng=None):
    rng = rng or np.random.RandomState(0)
    # field f draws from its own slice of the global id space
    per = num_features // num_fields
    ids = np.stack([
        rng.randint(f * per, (f + 1) * per, batch_size)
        for f in range(num_fields)
    ], axis=1).astype(np.int64)
    # clickiness depends on a hidden linear rule so the model can learn
    w = np.sin(np.arange(num_features) * 0.1)
    score = w[ids].sum(axis=1)
    label = (score > np.median(score)).astype(np.int64).reshape(-1, 1)
    return {"feat_ids": ids, "label": label}
