"""Word2vec CBOW-style model (reference: tests/book/test_word2vec.py /
tests/unittests/dist_word2vec.py: N-gram context words → embedding concat →
hidden → softmax over vocab)."""

import numpy as np

import paddle_tpu.fluid as fluid


def ngram_net(context_words, next_word, dict_size, embed_dim=32,
              hidden_size=256, is_train=True):
    """context_words: list of [B,1] int64 vars (N-gram context)."""
    embeds = [
        fluid.layers.embedding(
            input=w, size=[dict_size, embed_dim],
            param_attr=fluid.ParamAttr(name="shared_w"))
        for w in context_words
    ]
    embeds = [
        fluid.layers.reshape(e, shape=[-1, embed_dim]) for e in embeds
    ]
    concat = fluid.layers.concat(input=embeds, axis=1)
    hidden = fluid.layers.fc(input=concat, size=hidden_size, act="sigmoid")
    logits = fluid.layers.fc(input=hidden, size=dict_size, act=None)
    loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=next_word))
    return loss, logits


def get_model(dict_size=1000, embed_dim=32, hidden_size=128, window=4,
              lr=0.01, is_train=True):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        ctx_vars = [
            fluid.layers.data(name="w%d" % i, shape=[1], dtype="int64")
            for i in range(window)
        ]
        nxt = fluid.layers.data(name="next_word", shape=[1], dtype="int64")
        loss, logits = ngram_net(ctx_vars, nxt, dict_size, embed_dim,
                                 hidden_size, is_train)
        if is_train:
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    feeds = {v.name: v for v in ctx_vars}
    feeds["next_word"] = nxt
    return main, startup, {"feeds": feeds, "loss": loss, "logits": logits}


def make_fake_batch(batch_size, dict_size, window, rng=None):
    rng = rng or np.random.RandomState(0)
    ctx = rng.randint(0, dict_size, (batch_size, window)).astype(np.int64)
    # next word = deterministic function of context → learnable
    nxt = (ctx.sum(axis=1) % dict_size).astype(np.int64).reshape(-1, 1)
    feed = {"w%d" % i: ctx[:, i:i + 1] for i in range(window)}
    feed["next_word"] = nxt
    return feed
