"""VGG-16 (reference: benchmark/fluid/models/vgg.py vgg16_bn_drop)."""

import paddle_tpu.fluid as fluid


def conv_block(input, num_filter, groups, dropouts, is_train=True):
    h = input
    for i in range(groups):
        h = fluid.layers.conv2d(input=h, num_filters=num_filter,
                                filter_size=3, padding=1, act=None)
        h = fluid.layers.batch_norm(input=h, act="relu",
                                    is_test=not is_train)
        if dropouts[i] > 0:
            h = fluid.layers.dropout(x=h, dropout_prob=dropouts[i],
                                     is_test=not is_train)
    return fluid.layers.pool2d(input=h, pool_size=2, pool_stride=2,
                               pool_type="max")


def vgg16_bn_drop(input, is_train=True):
    c1 = conv_block(input, 64, 2, [0.3, 0], is_train)
    c2 = conv_block(c1, 128, 2, [0.4, 0], is_train)
    c3 = conv_block(c2, 256, 3, [0.4, 0.4, 0], is_train)
    c4 = conv_block(c3, 512, 3, [0.4, 0.4, 0], is_train)
    c5 = conv_block(c4, 512, 3, [0.4, 0.4, 0], is_train)
    d1 = fluid.layers.dropout(x=c5, dropout_prob=0.5, is_test=not is_train)
    fc1 = fluid.layers.fc(input=d1, size=512, act=None)
    bn = fluid.layers.batch_norm(input=fc1, act="relu",
                                 is_test=not is_train)
    d2 = fluid.layers.dropout(x=bn, dropout_prob=0.5, is_test=not is_train)
    fc2 = fluid.layers.fc(input=d2, size=512, act=None)
    return fc2


def get_model(batch_size=32, class_num=10, image_shape=(3, 32, 32), lr=0.01,
              is_train=True):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=list(image_shape),
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        feat = vgg16_bn_drop(img, is_train=is_train)
        logits = fluid.layers.fc(input=feat, size=class_num, act=None)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=label))
        acc = fluid.layers.accuracy(
            input=fluid.layers.softmax(logits), label=label)
        if is_train:
            opt = fluid.optimizer.Adam(learning_rate=lr)
            opt.minimize(loss)
    return main, startup, {"img": img, "label": label, "loss": loss,
                           "acc": acc, "logits": logits}
