"""Initializers appended as ops to the startup program
(reference: python/paddle/fluid/initializer.py — Constant/Uniform/Normal/
Xavier/MSRA/Bilinear/NumpyArray, each of which appends fill_constant /
uniform_random / gaussian_random ops)."""

import math

import numpy as np

from paddle_tpu.core.types import VarType


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value
        del force_cpu  # placement is XLA's; constants fold at compile

    def __call__(self, var, block):
        block.append_op(
            type="fill_constant",
            outputs={"Out": [var]},
            attrs={
                "shape": list(var.shape),
                "dtype": int(var.dtype),
                "value": float(self.value),
            },
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low = low
        self.high = high
        self.seed = seed

    def __call__(self, var, block):
        block.append_op(
            type="uniform_random",
            outputs={"Out": [var]},
            attrs={
                "shape": list(var.shape),
                "dtype": int(var.dtype),
                "min": float(self.low),
                "max": float(self.high),
                "seed": self.seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc = loc
        self.scale = scale
        self.seed = seed

    def __call__(self, var, block):
        block.append_op(
            type="gaussian_random",
            outputs={"Out": [var]},
            attrs={
                "shape": list(var.shape),
                "dtype": int(var.dtype),
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


class TruncatedNormalInitializer(NormalInitializer):
    def __call__(self, var, block):
        block.append_op(
            type="truncated_gaussian_random",
            outputs={"Out": [var]},
            attrs={
                "shape": list(var.shape),
                "dtype": int(var.dtype),
                "mean": float(self.loc),
                "std": float(self.scale),
                "seed": self.seed,
            },
        )


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return (shape[0] if shape else 1, shape[0] if shape else 1)
    fan_in = shape[0]
    fan_out = shape[1]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    return fan_in * receptive, fan_out * receptive


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.fan_out = fan_out
        self.seed = seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.seed = seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        arr = self.value
        if arr.dtype in (np.float32, np.float64, np.float16):
            key, vals = "fp32_values", [float(v) for v in arr.flatten()]
        else:
            key, vals = "int32_values", [int(v) for v in arr.flatten()]
        block.append_op(
            type="assign_value",
            outputs={"Out": [var]},
            attrs={
                "shape": list(arr.shape),
                "dtype": int(var.dtype),
                key: vals,
            },
        )


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernel init for conv_transpose."""

    def __call__(self, var, block):
        shape = var.shape
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        size = int(np.prod(shape))
        flat = np.zeros(size, dtype=np.float32)
        for i in range(size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        weight = flat.reshape(shape)
        NumpyArrayInitializer(weight)(var, block)


# Reference-compatible aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def force_init_on_cpu():
    return False


import contextlib as _contextlib


@_contextlib.contextmanager
def init_on_cpu():
    """(reference: initializer.py init_on_cpu) — forces init ops onto the
    host. Placement is XLA's under PJRT; kept as a no-op scope for script
    compatibility."""
    yield
