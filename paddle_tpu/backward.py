"""Program-level autodiff: append_backward.

Mirrors the reference's Python-side autodiff (reference:
python/paddle/fluid/backward.py:394 append_backward — find op path
:573, per-op grad descs :252, dedup repeated grads with sum ops :135,
no-grad pruning :204), but grad ops here carry forward-slot metadata so the
engine can derive their computation via ``jax.vjp`` of the forward lowering
(see engine/lowering.py) instead of hand-written grad kernels.
"""

from paddle_tpu.core.registry import OpRegistry
from paddle_tpu.framework import grad_var_name
from paddle_tpu import unique_name


def _find_op_path(block, target_name, no_grad_set):
    """Indices of ops that (transitively) produce ``target_name``, pruned of
    subtrees behind stop_gradient vars (reference: backward.py:573)."""
    relevant = [False] * len(block.desc.ops)
    needed = {target_name}
    for i in range(len(block.desc.ops) - 1, -1, -1):
        op = block.desc.ops[i]
        if any(n in needed for n in op.output_arg_names()):
            relevant[i] = True
            for n in op.input_arg_names():
                if n not in no_grad_set:
                    needed.add(n)
    return [i for i, r in enumerate(relevant) if r]


def _collect_no_grad(block, extra=None):
    s = set(extra or ())
    for name, vd in block.desc.vars.items():
        if vd.stop_gradient:
            s.add(name)
    return s


def _op_is_differentiable(op):
    if not OpRegistry.has(op.type):
        return False
    return OpRegistry.get(op.type).grad_maker is not None


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Append gradient ops for ``loss``; returns [(param, grad_var)]
    (reference: backward.py:394)."""
    from paddle_tpu.framework import OpRole

    block = loss.block
    program = block.program
    # Every op appended below is gradient machinery: stamp it Backward so
    # clone(for_test=True) prunes it (reference: backward.py:394 op_role).
    with program._op_role_guard(OpRole.Backward):
        return _append_backward_impl(
            loss, block, program, parameter_list, no_grad_set, callbacks
        )


def _append_backward_impl(loss, block, program, parameter_list, no_grad_set,
                          callbacks):
    from paddle_tpu.framework import OpRole

    no_grad = _collect_no_grad(block, no_grad_set)

    path = _find_op_path(block, loss.name, no_grad)
    path_set = set(path)

    # Vars whose gradient is needed: inputs/outputs of path ops not in no_grad
    grad_needed = set()
    for i in path:
        op = block.desc.ops[i]
        for n in op.input_arg_names() + op.output_arg_names():
            if n not in no_grad:
                grad_needed.add(n)

    # fill loss@GRAD = 1; a scalar loss (shape ()) keeps its scalar shape —
    # `loss.shape or [1]` would promote it to [1] and the grad var's IR
    # metadata would disagree with the forward var (the verifier's
    # grad-pairing checker caught this)
    loss_grad_name = grad_var_name(loss.name)
    seed_shape = list(loss.shape) if loss.shape is not None else [1]
    block.create_var(
        name=loss_grad_name,
        shape=seed_shape,
        dtype=loss.dtype,
        stop_gradient=True,
    )
    block.append_op(
        type="fill_constant",
        outputs={"Out": [loss_grad_name]},
        attrs={
            "shape": seed_shape,
            "dtype": int(loss.dtype),
            "value": 1.0,
            "__is_loss_grad__": True,
        },
    )

    # grad accumulation bookkeeping: var -> list of produced grad names
    contributions = {loss.name: [loss_grad_name]}

    def _materialize_grad(var_name):
        """Emit a sum op if var has multiple grad contributions; returns the
        final grad name or None (reference: _addup_repetitive_outputs_)."""
        contribs = contributions.get(var_name)
        if not contribs:
            return None
        gname = grad_var_name(var_name)
        if len(contribs) == 1:
            # first contribution is always named gname (see
            # _new_contribution_name), so no rename is needed
            return contribs[0]
        _ensure_grad_var(var_name, gname)
        block.append_op(
            type="sum", inputs={"X": list(contribs)}, outputs={"Out": [gname]}
        )
        contributions[var_name] = [gname]
        return gname

    def _ensure_grad_var(fwd_name, gname):
        if gname in block.desc.vars:
            return
        fv = block.desc.find_var_recursive(fwd_name)
        block.create_var(
            name=gname,
            shape=list(fv.shape) if fv is not None and fv.shape is not None else None,
            dtype=fv.dtype if fv is not None else "float32",
            stop_gradient=True,
        )

    def _new_contribution_name(var_name):
        contribs = contributions.setdefault(var_name, [])
        gname = grad_var_name(var_name)
        if not contribs:
            name = gname
        else:
            name = unique_name.generate(gname + "@RENAME")
        contribs.append(name)
        _ensure_grad_var(var_name, name)
        return name

    # reverse sweep
    for i in reversed(path):
        op = block.desc.ops[i]
        if not _op_is_differentiable(op):
            continue
        info = OpRegistry.get(op.type)

        # output grads this op can receive
        out_grad_inputs = {}
        has_any = False
        for slot, names in op.outputs.items():
            gnames = []
            for n in names:
                g = _materialize_grad(n) if n in contributions else None
                gnames.append(g)
            if any(g is not None for g in gnames):
                has_any = True
            out_grad_inputs[slot] = gnames
        if not has_any:
            continue

        # which inputs need grads
        grad_outputs = {}
        wants = False
        for slot, names in op.inputs.items():
            if slot in info.no_grad_inputs:
                continue
            gnames = []
            for n in names:
                vd = block.desc.find_var_recursive(n)
                if n in no_grad or (vd is not None and vd.stop_gradient and not _is_param(block, n)):
                    gnames.append(None)
                elif vd is not None and vd.dtype is not None and _is_int_dtype(vd.dtype):
                    gnames.append(None)
                elif n in grad_needed or _is_param(block, n):
                    gnames.append(_new_contribution_name(n))
                    wants = True
                else:
                    gnames.append(None)
        # prune empty
            slot_out = [g for g in gnames]
            if any(g is not None for g in slot_out):
                grad_outputs[slot + "@GRAD"] = [
                    g if g is not None else _dummy_sink(block, n)
                    for g, n in zip(slot_out, names)
                ]
        if not wants:
            continue

        grad_inputs = {}
        for slot, names in op.inputs.items():
            grad_inputs[slot] = list(names)
        # forward outputs the grad lowering consumes (saved statistics
        # etc. — reference: grad ops declaring forward outputs as inputs,
        # e.g. batch_norm_op.cc BatchNormGradOp's SavedMean/SavedVariance)
        for slot in getattr(info, "grad_needs_outputs", ()):
            if slot in op.output_names() and slot not in grad_inputs:
                grad_inputs[slot] = list(op.output(slot))
        for slot, gnames in out_grad_inputs.items():
            if any(g is not None for g in gnames):
                # Keep positions aligned with the forward op's output list;
                # absent grads become the engine's EMPTY placeholder so the
                # vjp cotangent for output i is never mispaired with output j.
                from paddle_tpu.engine.lowering import EMPTY_VAR_NAME

                grad_inputs[slot + "@GRAD"] = [
                    g if g is not None else EMPTY_VAR_NAME for g in gnames
                ]

        attrs = dict(op.attrs)
        attrs["op_role"] = OpRole.Backward
        attrs["__fwd_inputs__"] = sorted(op.inputs.keys())
        attrs["__fwd_outputs__"] = sorted(op.outputs.keys())
        if "__rng_id__" not in attrs:
            attrs["__rng_id__"] = i
            op.attrs["__rng_id__"] = i

        block.append_op(
            type=op.type + "_grad",
            inputs=grad_inputs,
            outputs=grad_outputs,
            attrs=attrs,
        )

    # finalize remaining multi-contribution grads (params and leaf inputs
    # alike) — their consumers are outside the block (optimizer ops, user
    # fetches), so the sum op goes at the end of the sweep
    for var_name in list(contributions):
        _materialize_grad(var_name)

    # finalize param grads
    if parameter_list is not None:
        params = [
            block.program.global_block().var(p) if isinstance(p, str) else p
            for p in parameter_list
        ]
    else:
        params = [p for p in program.all_parameters() if p.trainable]

    params_and_grads = []
    for p in params:
        g = _materialize_grad(p.name)
        if g is None:
            continue
        gvar = block.var(g) if g in block.vars else block.create_var(
            name=g, shape=list(p.shape), dtype=p.dtype, stop_gradient=True
        )
        params_and_grads.append((p, gvar))
    return params_and_grads


def _is_param(block, name):
    vd = block.desc.find_var_recursive(name)
    return vd is not None and vd.is_parameter


def _is_int_dtype(dtype):
    from paddle_tpu.core.types import VarType

    return dtype in (
        VarType.INT8,
        VarType.INT16,
        VarType.INT32,
        VarType.INT64,
        VarType.UINT8,
        VarType.BOOL,
    )


def _dummy_sink(block, fwd_name):
    name = unique_name.generate(fwd_name + "@GRAD@UNUSED")
    fv = block.desc.find_var_recursive(fwd_name)
    block.create_var(
        name=name,
        shape=list(fv.shape) if fv is not None and fv.shape is not None else None,
        dtype=fv.dtype if fv is not None else "float32",
        stop_gradient=True,
    )
    return name


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of targets w.r.t. inputs (reference: backward.py:613)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    assert len(targets) == 1, "calc_gradient currently supports one target"
    if target_gradients is not None:
        raise NotImplementedError(
            "calc_gradient with explicit target_gradients is not supported "
            "yet; gradients are seeded with ones"
        )
    pg = append_backward(
        targets[0],
        parameter_list=None,
        no_grad_set=no_grad_set,
    )
    block = targets[0].block
    outs = []
    for iv in inputs:
        gname = grad_var_name(iv.name)
        outs.append(block.var(gname) if block.has_var(gname) else None)
    return outs
