"""Places: device identity tags (reference: paddle/fluid/platform/place.h).

The TPU build's Place variant is {CPUPlace, TPUPlace}; ``CUDAPlace`` is kept
as an alias accepted for script compatibility (it selects the accelerator,
which here is the TPU chip). Device binding is resolved lazily through JAX's
backend — there is no dynload'd driver stack to manage (PJRT plays the role
of the reference's platform/dynload layer).
"""

import jax


class Place:
    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(self.__dict__.items()))))


class CPUPlace(Place):
    def __repr__(self):
        return "CPUPlace"

    def jax_device(self):
        cpus = [d for d in jax.devices() if d.platform == "cpu"]
        return cpus[0] if cpus else jax.devices()[0]


class TPUPlace(Place):
    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return "TPUPlace(%d)" % self.device_id

    def jax_device(self):
        devs = jax.devices()
        return devs[self.device_id % len(devs)]


class CUDAPinnedPlace(CPUPlace):
    def __repr__(self):
        return "CUDAPinnedPlace"


# Script-compatibility alias: "the accelerator" is the TPU in this build.
CUDAPlace = TPUPlace


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return True


def default_accelerator_place():
    devs = jax.devices()
    if devs and devs[0].platform != "cpu":
        return TPUPlace(0)
    return CPUPlace()


def cuda_device_count():
    """Accelerator count (name kept for API compat)."""
    return len([d for d in jax.devices() if d.platform != "cpu"]) or 1
