"""Legacy ParallelExecutor façade (reference:
python/paddle/fluid/parallel_executor.py:41) over the SPMD CompiledProgram
path — the C++ SSA-graph scheduler it used to wrap is replaced by one
XLA-compiled SPMD program (see compiler.py)."""

from paddle_tpu.compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from paddle_tpu.executor import Executor, global_scope
from paddle_tpu.framework import default_main_program
from paddle_tpu.platform import default_accelerator_place


class ParallelExecutor:
    """``dist_strategy`` selects the transport (default: the
    ``PADDLE_TPU_DIST_STRATEGY`` flag, else plain data parallelism):

    * ``""`` / ``"dp"`` — SPMD data parallelism over all local devices
      (a 1-axis dp mesh, parameters replicated).
    * ``"mesh"`` — GSPMD over an explicit ``mesh`` (or the
      ``PADDLE_TPU_MESH`` flag's) with ``shard_rules`` laying out
      parameters/optimizer state; gradient reduction is an in-graph
      psum under the dp axis derived by XLA's partitioner — no pserver
      round-trip (see README "Multi-chip GSPMD").
    """

    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None, dist_strategy=None,
                 mesh=None, shard_rules=None, data_axes=("dp",)):
        from paddle_tpu import flags

        self._program = main_program or default_main_program()
        self._scope = scope or global_scope()
        self._executor = Executor(default_accelerator_place())
        if dist_strategy is None:
            dist_strategy = flags.get_flag("dist_strategy")
        if dist_strategy == "mesh":
            from paddle_tpu.parallel.mesh import (get_default_mesh,
                                                  mesh_from_flag)

            if mesh is None:
                mesh = mesh_from_flag() or get_default_mesh()
            self._compiled = CompiledProgram(self._program).with_spmd(
                mesh=mesh, shard_rules=shard_rules, data_axes=data_axes,
                loss_name=loss_name)
        elif dist_strategy in ("", "dp"):
            self._compiled = CompiledProgram(self._program).with_data_parallel(
                loss_name=loss_name,
                build_strategy=build_strategy,
                exec_strategy=exec_strategy,
                share_vars_from=getattr(share_vars_from, "_compiled", None),
            )
        else:
            raise ValueError(
                "unknown dist_strategy %r; want '', 'dp', or 'mesh' "
                "(pserver/nccl2 go through DistributeTranspiler)"
                % dist_strategy)

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._compiled._run(
            self._executor, feed, fetch_list, self._scope, return_numpy
        )
