"""Legacy ParallelExecutor façade (reference:
python/paddle/fluid/parallel_executor.py:41) over the SPMD CompiledProgram
path — the C++ SSA-graph scheduler it used to wrap is replaced by one
XLA-compiled SPMD program (see compiler.py)."""

from paddle_tpu.compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from paddle_tpu.executor import Executor, global_scope
from paddle_tpu.framework import default_main_program
from paddle_tpu.platform import default_accelerator_place


class ParallelExecutor:
    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        self._program = main_program or default_main_program()
        self._scope = scope or global_scope()
        self._executor = Executor(default_accelerator_place())
        self._compiled = CompiledProgram(self._program).with_data_parallel(
            loss_name=loss_name,
            build_strategy=build_strategy,
            exec_strategy=exec_strategy,
            share_vars_from=getattr(share_vars_from, "_compiled", None),
        )

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._compiled._run(
            self._executor, feed, fetch_list, self._scope, return_numpy
        )
