"""AOT-serialized inference artifacts (VERDICT r3 Next #8; reference:
inference/api/analysis_predictor.cc:391,734 — the deploy path loads a
frozen program and runs WITHOUT the Python front-end re-building it).

TPU-native form: the pruned inference program is lowered once, its
parameters baked in as constants, and the whole function exported as a
serialized StableHLO module via ``jax.export``. The load path
deserializes and executes that module directly — no op registry, no
Program, no re-lowering; the first call pays only XLA's compile of an
already-lowered module (and nothing at all when the platform supports
compilation caches).

Artifact layout under the model dir:
    __aot__.stablehlo     jax.export serialization (params embedded)
    __aot_meta__.json     {"feed_names": [...], "fetch_names": [...],
                           "feeds": {name: {"shape", "dtype"}}}
"""

import json
import os

import numpy as np

__all__ = ["export_aot", "AotPredictor"]

_AOT_FILE = "__aot__.stablehlo"
_AOT_META = "__aot_meta__.json"


def export_aot(dirname, feeded_var_names, fetch_names, program, scope,
               example_feeds):
    """Lower the (already pruned, is_test) ``program`` and serialize it.

    ``example_feeds``: {name: array-like} fixing each feed's shape and
    dtype — the exported executable is specialized to these shapes, like
    the reference predictor's fixed-shape deployment artifacts.
    """
    import jax
    import jax.export  # noqa: F401  (submodule; plain `import jax` does
    # not load it, and bare attribute access trips jax's deprecation
    # __getattr__ with an AttributeError on the pinned jax)

    from paddle_tpu.engine.lowering import BlockProgram, lower_block

    missing = [n for n in feeded_var_names if n not in example_feeds]
    if missing:
        raise ValueError(
            "export_format='aot' needs example_feeds for every feed var "
            "to fix the exported shapes; missing %s" % missing)

    bp = BlockProgram(program.desc.global_block(), list(feeded_var_names),
                      list(fetch_names), [])
    fn = lower_block(bp, is_test=True)
    state = []
    for n in bp.state_in_names:
        v = scope.get(n)
        if v is None:
            raise RuntimeError(
                "var %r has no value in the scope; run startup/load "
                "before exporting" % n)
        state.append(np.asarray(v))

    def frozen(*feeds):
        fetches, _ = fn(list(feeds), state, jax.random.PRNGKey(0))
        return tuple(fetches)

    specs = []
    meta_feeds = {}
    for n in feeded_var_names:
        a = np.asarray(example_feeds[n])
        specs.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
        meta_feeds[n] = {"shape": list(a.shape), "dtype": str(a.dtype)}

    exported = jax.export.export(jax.jit(frozen))(*specs)
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, _AOT_FILE), "wb") as f:
        f.write(exported.serialize())
    with open(os.path.join(dirname, _AOT_META), "w") as f:
        json.dump({"feed_names": list(feeded_var_names),
                   "fetch_names": list(fetch_names),
                   "feeds": meta_feeds}, f)
    return fetch_names


def has_aot_artifact(dirname):
    return (os.path.exists(os.path.join(dirname, _AOT_FILE))
            and os.path.exists(os.path.join(dirname, _AOT_META)))


def remove_aot_artifact(dirname):
    for f in (_AOT_FILE, _AOT_META):
        try:
            os.remove(os.path.join(dirname, f))
        except OSError:
            pass


class AotPredictor:
    """Executes a serialized AOT artifact — never touches the op
    registry or the Program machinery (the 'without the Python
    front-end' property of analysis_predictor.cc's load path)."""

    def __init__(self, dirname):
        import jax
        import jax.export  # noqa: F401  (see export_aot)

        with open(os.path.join(dirname, _AOT_META)) as f:
            self._meta = json.load(f)
        with open(os.path.join(dirname, _AOT_FILE), "rb") as f:
            self._exported = jax.export.deserialize(bytearray(f.read()))
        self.platforms = tuple(self._exported.platforms)

    def runs_on(self, backend):
        """Whether the artifact was lowered for ``backend`` (an exported
        module is platform-specialized)."""
        return backend in self.platforms

    @property
    def feed_names(self):
        return list(self._meta["feed_names"])

    @property
    def fetch_names(self):
        return list(self._meta["fetch_names"])

    def run(self, feed):
        """feed: {name: array-like} at the exported shapes/dtypes."""
        args = []
        for n in self._meta["feed_names"]:
            spec = self._meta["feeds"][n]
            a = np.asarray(feed[n], dtype=np.dtype(spec["dtype"]))
            if list(a.shape) != spec["shape"]:
                raise ValueError(
                    "feed %r shape %s != exported shape %s (the AOT "
                    "artifact is shape-specialized)"
                    % (n, list(a.shape), spec["shape"]))
            args.append(a)
        return [np.asarray(o) for o in self._exported.call(*args)]
