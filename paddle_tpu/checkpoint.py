"""Async sharded checkpointing (SURVEY §5: the TPU-native equivalent of
the reference's save-op machinery — python/paddle/fluid/io.py:441
save_persistables + operators/save_combine_op.cc — re-designed as a
tensorstore-style background writer instead of save ops on the step
thread).

Why async is nearly free here: the "snapshot" phase on the step thread
dispatches one on-device copy per var and returns — copies are enqueued
on the device stream BEFORE the next step's donation can invalidate the
source buffers (the engine donates state buffers into the jitted step),
and the ~ms HBM copy never waits for the device->host transfer. The
transfer and file writes then run on a background thread while training
continues; host numpy values are captured by reference (nothing mutates
them — scope.set rebinds).

Layout of one checkpoint (written under a temp dir, atomically renamed):

    <root>/step_<N>/
        manifest.json     {"step": N, "vars": {name: {"file", "dtype",
                           "global_shape", "index"}}, "process": p}
        <var>.npy         one file per var (per addressable shard when
                          the array is sharded over a mesh)

``index`` records each saved piece's slice into the global shape, so a
multi-host restore can reassemble exactly like the reference's sliced
pserver checkpoints (distributed/ps.py does the same with @SHARD_START).

Cross-root replication + quorum (elastic capacity): with
``replica_roots`` configured and ``PADDLE_TPU_CKPT_REPLICAS`` (or the
``replicas`` ctor arg) > 0, the writer mirrors each published step dir
to up to k peer roots, byte-for-byte, under
``<peer_root>/.replicas/<basename(my_root)>/`` — the same atomic
tmp+rename publication, so a peer never sees a half replica. Reads then
become a majority vote over (local root + replica locations): a torn
local-only save — published locally, crashed before mirroring — cannot
win ``latest_step()``, and a rank whose local root died (``disk_fail``)
restores its shards from a peer's replica, byte-identical. Replication
off (the default) leaves single-root behavior exactly as before.
"""

import json
import os
import re
import shutil
import threading
import warnings

import numpy as np

__all__ = ["CheckpointManager"]

_STEP_RE = re.compile(r"^step_(\d+)(?:\.proc(\d+))?$")


class _ShardMissingError(FileNotFoundError):
    """A step that looks complete (manifest present) lost a shard file
    at every location holding it — restore falls back a step."""


def _read_manifest(step_dir):
    """The dir's parsed manifest.json, or None when it is missing,
    truncated, or unparsable — the signature of a crash mid-write
    (pre-atomic-rename layouts, torn NFS renames). A None manifest
    makes the dir invisible to restore/latest_step, so recovery falls
    back to the previous COMPLETE step instead of raising into the
    face of a supervisor that is trying to restart the job."""
    path = os.path.join(step_dir, "manifest.json")
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        warnings.warn(
            "skipping checkpoint dir %s: corrupt manifest (%s)"
            % (step_dir, e), RuntimeWarning)
        from paddle_tpu import observability as obs

        obs.inc("recovery.ckpt_corrupt")
        obs.event("ckpt.corrupt_manifest", dir=step_dir,
                  error=str(e)[:200])
        return None


def _covers_global(idx, global_shape):
    return idx is None or all(a == 0 and b == dim for (a, b), dim
                              in zip(idx, global_shape))


def _save_synced(path, arr):
    """np.save + fsync: the atomic-rename publication is only crash-safe
    if the DATA pages are durable before the rename, not just the
    manifest."""
    with open(path, "wb") as f:
        np.save(f, arr)
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _slice_index(shard, global_shape):
    """[(start, stop), ...] per dim of a jax Shard's slice into the
    global array."""
    out = []
    for dim, sl in enumerate(shard.index):
        start = 0 if sl.start is None else int(sl.start)
        stop = (global_shape[dim] if sl.stop is None else int(sl.stop))
        out.append((start, stop))
    return out


class CheckpointManager:
    """Background-thread checkpoint writer with atomic publication.

    save() captures an off-critical-path snapshot and returns
    immediately; the device->host transfer + file writes happen on ONE
    persistent daemon writer thread consuming a bounded pending queue —
    so the step thread never joins the PREVIOUS save either (the PR 5
    design joined it inside save(); that join was the residual
    checkpoint wall this completes the removal of). The snapshot cost
    the step thread still pays is dispatching one on-device copy per
    var (~ms; must happen before the next step's donation invalidates
    the source buffers) plus kicking off the D2H transfer with
    ``copy_to_host_async`` so it overlaps training instead of starting
    when the writer gets around to ``np.asarray``.

    A checkpoint directory appears under its final name only when
    complete (write to ``.tmp_step_N``, fsync, ``os.rename``) — a crash
    mid-save can never publish a half checkpoint, the property the
    reference gets from writing params into place one save op at a time
    and loses on crash. The single writer publishes saves in submission
    order. ``max_pending`` bounds snapshot memory: a checkpoint interval
    shorter than the write time degrades toward synchronous saving
    (save() blocks until the queue drains below the bound) rather than
    piling up device snapshots.
    """

    def __init__(self, root, max_to_keep=3, process_index=None,
                 process_count=None, max_pending=2, replica_roots=None,
                 replicas=None):
        from paddle_tpu import flags

        self.root = root
        self.max_to_keep = max_to_keep
        self.max_pending = max(1, int(max_pending))
        # cross-root replication: this rank's shards mirror to up to
        # ``replicas`` of the given peer roots after each local publish
        # (0 / no peers = off; reads stay single-root)
        if replicas is None:
            replicas = int(flags.get_flag("ckpt_replicas"))
        self.replicas = max(0, int(replicas))
        self.replica_roots = [
            r for r in (replica_roots or [])
            if os.path.abspath(r) != os.path.abspath(root)]
        # process identity resolves LAZILY at first save: querying jax
        # here would initialize the backend, poisoning a later
        # jax.distributed.initialize() when the manager is constructed
        # first (the natural script order)
        self._proc = (process_index, process_count)
        os.makedirs(root, exist_ok=True)
        self._error = None
        self._cv = threading.Condition()
        self._pending = []      # [(step, snapshot)] consumed in order
        self._writing = False
        self._writer = None     # the persistent daemon thread

    def _resolve_proc(self):
        pi, pc = self._proc
        if pi is None or pc is None:
            import jax

            pi = jax.process_index() if pi is None else pi
            pc = jax.process_count() if pc is None else pc
            self._proc = (pi, pc)
        return pi, pc

    @property
    def process_index(self):
        return self._resolve_proc()[0]

    @property
    def process_count(self):
        return self._resolve_proc()[1]

    def _dirname(self, step):
        """Single-process keeps the plain 'step_N' layout; multi-host
        processes each publish their own 'step_N.procI' directory so
        saves on a shared filesystem never collide (each process writes
        only the shards it OWNS — the tensorstore-style layout SURVEY §5
        prescribes)."""
        pi, pc = self._resolve_proc()
        if pc <= 1:
            return os.path.join(self.root, "step_%d" % step)
        return os.path.join(self.root, "step_%d.proc%d" % (step, pi))

    # -- save --------------------------------------------------------------
    def save(self, step, arrays, blocking=False):
        """``arrays``: {name: array-like}. Captures a snapshot now (an
        async on-device copy per jax array + an async D2H kickoff — the
        step thread's only cost), enqueues it for the persistent writer
        thread, and returns without joining any in-flight write. Raises
        any previous save's error (like orbax: a failed async save
        surfaces on the next interaction). A full pending queue
        (``max_pending``) blocks until the writer drains — bounded
        memory over unbounded pile-up."""
        import time as _time

        from paddle_tpu import observability as obs

        self.check_error()
        t0 = _time.perf_counter()
        snapshot = {}
        for name, arr in arrays.items():
            if hasattr(arr, "addressable_shards"):
                # jax array: async on-device copy (the original may be
                # a DONATED buffer the next training step deletes), then
                # start the device->host transfer NOW so it overlaps
                # training instead of the writer's np.asarray paying it
                cp = arr.copy()
                try:
                    cp.copy_to_host_async()
                except Exception:      # backend-dependent; best-effort
                    pass
                snapshot[name] = cp
            else:
                # host values: reference capture (nothing mutates them —
                # scope.set rebinds)
                snapshot[name] = arr
        obs.observe("ckpt.snapshot_ms",
                    (_time.perf_counter() - t0) * 1000.0)
        with self._cv:
            self._ensure_writer()
            self._pending.append((int(step), snapshot))
            obs.set_gauge("ckpt.pending", len(self._pending))
            self._cv.notify_all()
            while len(self._pending) > self.max_pending:
                obs.inc("ckpt.backpressure_waits")
                self._cv.wait()
        if blocking:
            self.wait()
            self.check_error()

    def _ensure_writer(self):
        """Start (or restart, should it ever die) the persistent writer
        under self._cv."""
        if self._writer is None or not self._writer.is_alive():
            self._writer = threading.Thread(
                target=self._writer_loop, name="paddle-tpu-ckpt-writer",
                daemon=True)
            self._writer.start()

    def _writer_loop(self):
        while True:
            with self._cv:
                while not self._pending:
                    self._cv.wait()
                step, snapshot = self._pending.pop(0)
                self._writing = True
                self._cv.notify_all()
            try:
                self._write(step, snapshot)
            finally:
                with self._cv:
                    self._writing = False
                    self._cv.notify_all()

    def _write(self, step, snapshot):
        """Writer-thread entry: the write attempt runs under the
        shared retry policy (resilience.retrying) so transient
        filesystem errors — or an injected ckpt_write fault — cost a
        backoff-spaced re-attempt, not the checkpoint. Each attempt
        restarts from a clean tmp dir; only exhaustion surfaces via
        check_error()."""
        from paddle_tpu.resilience.faultinject import InjectedFault
        from paddle_tpu.resilience.retrying import Backoff, retry_call

        def _on_retry(e, attempt, delay):
            from paddle_tpu import observability as obs

            obs.inc("recovery.ckpt_retry")
            obs.event("ckpt.write_retry", step=step, attempt=attempt,
                      error=str(e)[:200])

        try:
            retry_call(self._write_attempt, step, snapshot,
                       retry_on=(OSError, InjectedFault), attempts=3,
                       backoff=Backoff(base=0.05, cap=1.0, jitter=0.5,
                                       seed=step),
                       on_retry=_on_retry)
        except Exception as e:                        # noqa: BLE001
            self._error = e
            return
        # replicate AFTER the local publish succeeded, still on the
        # writer thread (a blocking save's wait() covers the mirror
        # too). Best-effort: a dead peer costs this step its quorum
        # vote there, never the local checkpoint.
        self._mirror(step)

    def _write_attempt(self, step, snapshot):
        final = self._dirname(step)
        tmp = os.path.join(self.root,
                           "." + os.path.basename(final) + ".tmp")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        pi, pc = self._resolve_proc()
        manifest = {"step": step, "process": pi,
                    "process_count": pc, "vars": {}}
        for name, arr in snapshot.items():
            shards = getattr(arr, "addressable_shards", None)
            fname = name.replace("/", "__")
            if shards is None:
                # plain host value: process 0 alone writes it
                if pi == 0:
                    host = np.asarray(arr)
                    _save_synced(os.path.join(tmp, fname + ".npy"),
                                 host)
                    manifest["vars"][name] = {
                        "global_shape": list(host.shape),
                        "dtype": str(host.dtype),
                        "pieces": [{"file": fname + ".npy",
                                    "index": None}],
                    }
                continue
            # One writer per DISTINCT slice across the whole mesh:
            # the lowest process index holding a slice owns it
            # (replicated arrays and tp-sharded-but-dp-replicated
            # params are written exactly once cluster-wide, not once
            # per process)
            owner = {}
            for dev, idx in arr.sharding.devices_indices_map(
                    arr.shape).items():
                key = tuple(
                    (0 if s.start is None else int(s.start),
                     arr.shape[d] if s.stop is None else int(s.stop))
                    for d, s in enumerate(idx))
                p = getattr(dev, "process_index", 0)
                if key not in owner or p < owner[key]:
                    owner[key] = p
            written = set()
            for sh in shards:
                key = tuple(map(tuple,
                                _slice_index(sh, arr.shape)))
                if key in written or owner.get(key) != pi:
                    continue
                written.add(key)
                piece = np.asarray(sh.data)       # D2H here
                full = _covers_global(key, arr.shape)
                pfile = (fname + ".npy" if full
                         else "%s.shard%d.npy" % (fname,
                                                  sh.device.id))
                _save_synced(os.path.join(tmp, pfile), piece)
                manifest["vars"].setdefault(name, {
                    "global_shape": list(arr.shape),
                    "dtype": str(piece.dtype),
                    "pieces": [],
                })["pieces"].append(
                    {"file": pfile,
                     "index": None if full else list(map(list,
                                                         key))})
        # fault point at the mid-write seam: var files exist, manifest
        # does not yet — the state a crash here leaves behind is exactly
        # what _read_manifest's fallback is for
        from paddle_tpu.resilience.faultinject import fault_point

        fault_point("ckpt_write", step=step)
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)                # file entries durable pre-rename
        shutil.rmtree(final, ignore_errors=True)
        os.rename(tmp, final)                     # atomic publish
        # a re-save of the same step under a DIFFERENT world size
        # must not leave the other layout's dirs to shadow this one
        # at restore time (process 0 cleans; peers' same-layout proc
        # dirs are of course kept)
        mine = os.path.basename(final)
        if pi == 0:
            for d in os.listdir(self.root):
                m = _STEP_RE.match(d)
                if not m or int(m.group(1)) != step or d == mine:
                    continue
                other_layout = (m.group(2) is not None) != (pc > 1)
                if other_layout:
                    shutil.rmtree(os.path.join(self.root, d),
                                  ignore_errors=True)
        _fsync_dir(self.root)                     # durable dir entry
        self._gc()

    def _gc(self):
        steps = self.all_steps()
        if not self.max_to_keep or not steps:
            return
        kept = steps[-self.max_to_keep:]
        # prune everything OLDER than the kept window — including
        # incomplete orphans from crashed saves, which never appear in
        # all_steps and would otherwise accumulate forever. Dirs newer
        # than the newest complete step are in-progress peers: kept.
        for d in os.listdir(self.root):
            m = _STEP_RE.match(d)
            if m and int(m.group(1)) < kept[0]:
                shutil.rmtree(os.path.join(self.root, d),
                              ignore_errors=True)

    # -- replication -------------------------------------------------------
    def _replica_dirs(self):
        """The peer locations this rank's steps mirror to (empty =
        replication off). Namespaced by the local root's basename so
        several ranks can share one peer root without colliding."""
        if not self.replicas or not self.replica_roots:
            return []
        base = os.path.basename(os.path.abspath(self.root))
        return [os.path.join(r, ".replicas", base)
                for r in self.replica_roots[:self.replicas]]

    def _mirror(self, step):
        """Copy the just-published step dir(s) to each replica location
        with the same tmp+rename atomic publication, then apply the
        max_to_keep window there. Writer-thread only."""
        from paddle_tpu import observability as obs

        final = self._dirname(step)
        base = os.path.basename(final)
        if not os.path.isdir(final):
            return
        for rd in self._replica_dirs():
            try:
                os.makedirs(rd, exist_ok=True)
                tmp = os.path.join(rd, "." + base + ".tmp")
                shutil.rmtree(tmp, ignore_errors=True)
                shutil.copytree(final, tmp)
                _fsync_dir(tmp)
                dst = os.path.join(rd, base)
                shutil.rmtree(dst, ignore_errors=True)
                os.rename(tmp, dst)
                _fsync_dir(rd)
                if self.max_to_keep:
                    have = sorted(
                        int(m.group(1)) for m in
                        (_STEP_RE.match(d) for d in os.listdir(rd)) if m)
                    cut = (have[-self.max_to_keep:] or [0])[0]
                    for d in os.listdir(rd):
                        m = _STEP_RE.match(d)
                        if m and int(m.group(1)) < cut:
                            shutil.rmtree(os.path.join(rd, d),
                                          ignore_errors=True)
            except OSError as e:
                warnings.warn(
                    "checkpoint replica to %s failed (%s) — step %d has "
                    "no quorum vote there" % (rd, e, step),
                    RuntimeWarning)
                obs.inc("recovery.ckpt_replica_failed")
                obs.event("ckpt.replica_failed", step=step, dest=rd,
                          error=str(e)[:200])
                continue
            obs.inc("recovery.ckpt_replicated")
            obs.event("ckpt.replicated", step=step, dest=rd)

    # -- lifecycle ---------------------------------------------------------
    def wait(self):
        """Block until every enqueued save has been written (the
        ResilientDriver's join-the-snapshot rollback seam)."""
        with self._cv:
            while self._pending or self._writing:
                self._cv.wait()

    def check_error(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint save failed") from err

    @property
    def in_flight(self):
        with self._cv:
            return bool(self._pending or self._writing)

    # -- restore -----------------------------------------------------------
    def _step_dirs(self, step=None, root=None):
        """{step: [(dir, manifest), ...]} of COMPLETE checkpoints (every
        process dir named by the recorded process_count must be present,
        every manifest readable — a missing/truncated/unparsable
        manifest marks a mid-write crash and hides the dir, see
        _read_manifest). When a root holds BOTH layouts for one step
        (re-saved under a different world size and the cleanup raced),
        the set with the newest manifest wins — never a silent mix.
        ``root`` defaults to the local root; quorum reads pass a
        replica location instead."""
        root = self.root if root is None else root
        found = {}
        try:
            entries_on_disk = os.listdir(root)
        except OSError:
            return {}        # location gone entirely (dead disk/peer)
        for d in entries_on_disk:
            m = _STEP_RE.match(d)
            if not m:
                continue
            s = int(m.group(1))
            if step is not None and s != step:
                continue
            path = os.path.join(root, d)
            manifest = _read_manifest(path)
            if manifest is None:
                continue
            is_proc = m.group(2) is not None
            found.setdefault(s, {}).setdefault(is_proc, []).append(
                (path, manifest))
        complete = {}
        for s, by_layout in found.items():
            candidates = []
            for entries in by_layout.values():
                entries = sorted(entries)
                want = entries[0][1].get("process_count", 1)
                if len(entries) < want:
                    continue
                try:
                    newest = max(os.path.getmtime(
                        os.path.join(d, "manifest.json"))
                        for d, _ in entries)
                except OSError:
                    continue        # dir raced away under a peer's gc
                candidates.append((newest, entries))
            if candidates:
                complete[s] = max(candidates)[1]
        return complete

    def all_steps(self):
        """Sorted complete steps. Single-root: exactly the local dirs.
        With replication configured: a majority vote over the locations
        that hold ANY complete step (an empty/poisoned location is not
        a voter — else a wiped disk would veto the surviving replicas)
        — a step published on a minority of locations (the torn-save
        signature: local publish, crash before mirror) does not
        appear."""
        replica_dirs = self._replica_dirs()
        if not replica_dirs:
            return sorted(self._step_dirs())
        votes = {}
        voters = 0
        for loc in [self.root] + replica_dirs:
            steps = set(self._step_dirs(root=loc))
            if not steps:
                continue
            voters += 1
            for s in steps:
                votes[s] = votes.get(s, 0) + 1
        if not voters:
            return []
        need = voters // 2 + 1
        return sorted(s for s, v in votes.items() if v >= need)

    def latest_step(self):
        steps = self.all_steps()
        best = steps[-1] if steps else None
        if self._replica_dirs():
            # a local step NEWER than the quorum winner lost the vote —
            # the torn-save forensic record (ckpt.quorum_reject)
            torn = [s for s in sorted(self._step_dirs())
                    if best is None or s > best]
            if torn:
                from paddle_tpu import observability as obs

                obs.inc("recovery.ckpt_quorum_reject")
                obs.event("ckpt.quorum_reject", steps=torn, chosen=best)
        return best

    def restore(self, step=None):
        """-> {name: np.ndarray} reassembled to global shape, merging
        every process's manifest (multi-host layouts).

        Degraded-read ladder: the local root is tried first; a step
        whose local dir lost a shard file (bit rot, partial disk loss)
        or is gone entirely is retried from each replica location
        (``ckpt.quorum_restore`` — byte-identical, the mirror is a
        file copy); only when NO location can serve the step does
        restore fall back to the previous complete step
        (``ckpt.missing_shard`` + ``ckpt.restore_fallback``, mirroring
        the corrupt-manifest fallback). An EXPLICITLY requested step
        that is absent everywhere still raises — only a step that
        looks complete but cannot be read falls back."""
        explicit = step is not None
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint under %s" % self.root)
        steps = self.all_steps()
        tries = [step] + [s for s in reversed(steps) if s < step]
        last_err = None
        for i, s in enumerate(tries):
            try:
                out = self._restore_step(s)
            except _ShardMissingError as e:
                from paddle_tpu import observability as obs

                obs.inc("recovery.ckpt_restore_fallback")
                obs.event("ckpt.restore_fallback", step=s,
                          error=str(e)[:200])
                last_err = e
                continue
            except FileNotFoundError as e:
                if i == 0 and explicit:
                    raise        # the requested step never existed
                last_err = e
                continue
            if i > 0:
                warnings.warn(
                    "checkpoint step %s unreadable; restored step %s "
                    "instead" % (step, s), RuntimeWarning)
            return out
        raise FileNotFoundError(
            "no readable checkpoint under %s (tried steps %s)"
            % (self.root, tries)) from last_err

    def _restore_step(self, step):
        """Load one step, trying the local root then each replica
        location. Raises FileNotFoundError when no location holds the
        step, _ShardMissingError when every location that holds it is
        missing a shard file."""
        shard_err = None
        for li, loc in enumerate([self.root] + self._replica_dirs()):
            entries = self._step_dirs(step, root=loc).get(step)
            if not entries:
                continue
            try:
                out = self._load_entries(entries)
            except (FileNotFoundError, OSError, ValueError) as e:
                from paddle_tpu import observability as obs

                warnings.warn(
                    "checkpoint step %d at %s is missing a shard file "
                    "(%s)" % (step, loc, e), RuntimeWarning)
                obs.inc("recovery.ckpt_missing_shard")
                obs.event("ckpt.missing_shard", step=step, location=loc,
                          error=str(e)[:200])
                shard_err = e
                continue
            if li > 0:
                from paddle_tpu import observability as obs

                obs.inc("recovery.ckpt_quorum_restore")
                obs.event("ckpt.quorum_restore", step=step, source=loc)
            return out
        if shard_err is not None:
            raise _ShardMissingError(
                "checkpoint step %s unreadable at every location"
                % step) from shard_err
        raise FileNotFoundError(
            "checkpoint step %s incomplete or absent under %s"
            % (step, self.root))

    @staticmethod
    def _load_entries(entries):
        out = {}
        filled = {}
        for d, manifest in entries:
            for name, spec in manifest["vars"].items():
                pieces = spec["pieces"]
                if name not in out:
                    if (len(pieces) == 1 and pieces[0]["index"] is None
                            and len(entries) == 1):
                        out[name] = np.load(
                            os.path.join(d, pieces[0]["file"]))
                        continue
                    out[name] = np.zeros(spec["global_shape"],
                                         np.dtype(spec["dtype"]))
                    filled[name] = set()
                full = out[name]
                for p in pieces:
                    key = (None if p["index"] is None
                           else tuple(map(tuple, p["index"])))
                    if key in filled.get(name, set()):
                        continue   # replicated piece seen from a peer
                    arr = np.load(os.path.join(d, p["file"]))
                    sl = (tuple(slice(a, b) for a, b in p["index"])
                          if p["index"] is not None else Ellipsis)
                    full[sl] = arr
                    filled.setdefault(name, set()).add(key)
        return out
