"""Async sharded checkpointing (SURVEY §5: the TPU-native equivalent of
the reference's save-op machinery — python/paddle/fluid/io.py:441
save_persistables + operators/save_combine_op.cc — re-designed as a
tensorstore-style background writer instead of save ops on the step
thread).

Why async is nearly free here: the "snapshot" phase on the step thread
dispatches one on-device copy per var and returns — copies are enqueued
on the device stream BEFORE the next step's donation can invalidate the
source buffers (the engine donates state buffers into the jitted step),
and the ~ms HBM copy never waits for the device->host transfer. The
transfer and file writes then run on a background thread while training
continues; host numpy values are captured by reference (nothing mutates
them — scope.set rebinds).

Layout of one checkpoint (written under a temp dir, atomically renamed):

    <root>/step_<N>/
        manifest.json     {"step": N, "vars": {name: {"file", "dtype",
                           "global_shape", "index"}}, "process": p}
        <var>.npy         one file per var (per addressable shard when
                          the array is sharded over a mesh)

``index`` records each saved piece's slice into the global shape, so a
multi-host restore can reassemble exactly like the reference's sliced
pserver checkpoints (distributed/ps.py does the same with @SHARD_START).
"""

import json
import os
import re
import shutil
import threading

import numpy as np

__all__ = ["CheckpointManager"]

_STEP_RE = re.compile(r"^step_(\d+)$")


def _save_synced(path, arr):
    """np.save + fsync: the atomic-rename publication is only crash-safe
    if the DATA pages are durable before the rename, not just the
    manifest."""
    with open(path, "wb") as f:
        np.save(f, arr)
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _slice_index(shard, global_shape):
    """[(start, stop), ...] per dim of a jax Shard's slice into the
    global array."""
    out = []
    for dim, sl in enumerate(shard.index):
        start = 0 if sl.start is None else int(sl.start)
        stop = (global_shape[dim] if sl.stop is None else int(sl.stop))
        out.append((start, stop))
    return out


class CheckpointManager:
    """Background-thread checkpoint writer with atomic publication.

    save() captures array references and returns immediately; the
    transfer + write happens on a daemon thread. A checkpoint directory
    appears under its final name only when complete (write to
    ``.tmp_step_N``, fsync, ``os.rename``) — a crash mid-save can never
    publish a half checkpoint, the property the reference gets from
    writing params into place one save op at a time and loses on crash.
    """

    def __init__(self, root, max_to_keep=3, process_index=0):
        self.root = root
        self.max_to_keep = max_to_keep
        self.process_index = process_index
        os.makedirs(root, exist_ok=True)
        self._thread = None
        self._error = None
        self._lock = threading.Lock()

    # -- save --------------------------------------------------------------
    def save(self, step, arrays, blocking=False):
        """``arrays``: {name: array-like}. Captures a snapshot now, writes
        in the background. One save is in flight at a time: if the
        PREVIOUS save is still writing, this call first joins it (so a
        checkpoint interval shorter than the write time degrades to
        synchronous saving rather than piling up threads). Raises any
        previous save's error (like orbax: a failed async save surfaces
        on the next interaction)."""
        self.check_error()
        self.wait()                      # one in-flight save at a time
        snapshot = {}
        for name, arr in arrays.items():
            # jax arrays: async on-device copy (the original may be a
            # DONATED buffer the next training step deletes); host
            # values: reference capture
            snapshot[name] = (arr.copy()
                              if hasattr(arr, "addressable_shards")
                              else arr)
        t = threading.Thread(
            target=self._write, args=(int(step), snapshot), daemon=True)
        with self._lock:
            self._thread = t
        t.start()
        if blocking:
            self.wait()
            self.check_error()

    def _write(self, step, snapshot):
        try:
            tmp = os.path.join(self.root, ".tmp_step_%d" % step)
            final = os.path.join(self.root, "step_%d" % step)
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            manifest = {"step": step, "process": self.process_index,
                        "vars": {}}
            for name, arr in snapshot.items():
                shards = getattr(arr, "addressable_shards", None)
                fname = name.replace("/", "__")
                shards = [] if shards is None else list(shards)
                # dedup by slice index: a dp-replicated param has N
                # identical full-range shards — save ONE piece, not N
                # copies of the whole array
                uniq = {}
                for sh in shards:
                    uniq.setdefault(
                        tuple(map(tuple, _slice_index(sh, arr.shape))),
                        sh)
                if len(uniq) > 1:
                    for sh in uniq.values():
                        idx = _slice_index(sh, arr.shape)
                        piece = np.asarray(sh.data)   # D2H here
                        pfile = "%s.shard%d.npy" % (fname, sh.device.id)
                        _save_synced(os.path.join(tmp, pfile), piece)
                        manifest["vars"].setdefault(name, {
                            "global_shape": list(arr.shape),
                            "dtype": str(piece.dtype),
                            "pieces": [],
                        })["pieces"].append(
                            {"file": pfile, "index": idx})
                else:
                    host = np.asarray(arr)            # D2H here
                    _save_synced(os.path.join(tmp, fname + ".npy"), host)
                    manifest["vars"][name] = {
                        "global_shape": list(host.shape),
                        "dtype": str(host.dtype),
                        "pieces": [{"file": fname + ".npy",
                                    "index": None}],
                    }
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(tmp)                # file entries durable pre-rename
            shutil.rmtree(final, ignore_errors=True)
            os.rename(tmp, final)                     # atomic publish
            _fsync_dir(self.root)                     # durable dir entry
            self._gc()
        except Exception as e:                        # noqa: BLE001
            self._error = e

    def _gc(self):
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep] if self.max_to_keep else []:
            shutil.rmtree(os.path.join(self.root, "step_%d" % s),
                          ignore_errors=True)

    # -- lifecycle ---------------------------------------------------------
    def wait(self):
        with self._lock:
            t = self._thread
        if t is not None:
            t.join()

    def check_error(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint save failed") from err

    @property
    def in_flight(self):
        with self._lock:
            t = self._thread
        return t is not None and t.is_alive()

    # -- restore -----------------------------------------------------------
    def all_steps(self):
        steps = []
        for d in os.listdir(self.root):
            m = _STEP_RE.match(d)
            if m and os.path.exists(
                    os.path.join(self.root, d, "manifest.json")):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, step=None):
        """-> {name: np.ndarray} reassembled to global shape."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint under %s" % self.root)
        d = os.path.join(self.root, "step_%d" % step)
        with open(os.path.join(d, "manifest.json")) as f:
            manifest = json.load(f)
        out = {}
        for name, spec in manifest["vars"].items():
            pieces = spec["pieces"]
            if len(pieces) == 1 and pieces[0]["index"] is None:
                out[name] = np.load(os.path.join(d, pieces[0]["file"]))
                continue
            full = np.zeros(spec["global_shape"],
                            np.dtype(spec["dtype"]))
            for p in pieces:
                arr = np.load(os.path.join(d, p["file"]))
                sl = tuple(slice(a, b) for a, b in p["index"])
                full[sl] = arr
            out[name] = full
        return out
