"""DistributeTranspiler (reference:
python/paddle/fluid/transpiler/distribute_transpiler.py:161).

The reference rewrites programs for two transports: parameter-server
(param blocks sliced across pservers, trainer send/recv + barriers,
listen_and_serv optimizer blocks — :280-952) and collective "nccl2"
(:226-244, gen_nccl_id bootstrap). TPU-native:

* collective mode needs NO program rewriting — the multi-host collective is
  the SAME compiled program over a DCN-spanning mesh; `transpile` wires the
  coordinator env (paddle_tpu.parallel.env.init_distributed plays
  gen_nccl_id) and `get_trainer_program` returns the program unchanged.
* pserver mode is reproduced structurally: params are round-robin assigned
  to pserver endpoints, the pserver program gets one optimizer sub-block
  per owned param (the listen_and_serv body), and the trainer program's
  optimizer ops for remote params are replaced by send/recv markers. The
  live RPC transport rides the host parameter service (see
  paddle_tpu.distributed; in-process execution of both programs is fully
  functional for tests, matching the reference's
  multi-process-on-localhost test topology).
"""

from paddle_tpu.framework import OP_ROLE_KEY, OpRole


class DistributeTranspilerConfig:
    """(reference: distribute_transpiler.py:130)"""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    mode = "pserver"

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192


class RoundRobin:
    """(reference: ps_dispatcher.py)"""

    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._i = 0

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._i % len(self._eps)])
            self._i += 1
        return out


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._mode = None
        self._param_to_ep = {}

    # -- entry point -------------------------------------------------------
    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None, current_endpoint=""):
        from paddle_tpu.framework import default_main_program

        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.origin_program = program or default_main_program()
        self.origin_startup = startup_program

        if isinstance(trainers, str) or self.config.mode == "nccl2":
            # collective mode: endpoints string in `trainers`
            self._mode = "collective"
            self._endpoints = (
                trainers.split(",") if isinstance(trainers, str) else [])
            return

        self._mode = "pserver"
        self.pserver_endpoints = [p for p in pservers.split(",") if p]
        dispatcher = (self.config.split_method or RoundRobin)(
            self.pserver_endpoints)
        params = [
            p.name for p in self.origin_program.all_parameters()
        ]
        eps = dispatcher.dispatch(params)
        self._param_to_ep = dict(zip(params, eps))

    # -- collective --------------------------------------------------------
    def get_trainer_program(self, wait_port=True):
        if self._mode == "collective":
            return self.origin_program
        return self._build_trainer_program()

    # -- pserver -----------------------------------------------------------
    def _ops_for_param(self, pname):
        """Optimizer-role ops whose op_role_var mentions the param."""
        block = self.origin_program.desc.global_block()
        out = []
        for op in block.ops:
            role = int(op.attrs.get(OP_ROLE_KEY, 0))
            if not role & OpRole.Optimize:
                continue
            rv = op.attrs.get("op_role_var", [])
            if any(v == pname or v == pname + "@GRAD" for v in rv):
                out.append(op)
        return out

    def _build_trainer_program(self):
        """Trainer keeps forward+backward; optimizer ops for params owned by
        remote pservers are replaced by send/recv markers (reference:
        get_trainer_program:554)."""
        trainer = self.origin_program.clone()
        block = trainer.desc.global_block()
        remote_params = set(self._param_to_ep)
        new_ops = []
        sent = set()
        for op in block.ops:
            role = int(op.attrs.get(OP_ROLE_KEY, 0))
            rv = op.attrs.get("op_role_var", [])
            owned = [v for v in rv if v in remote_params]
            if role & OpRole.Optimize and owned:
                pname = owned[0]
                if pname not in sent:
                    sent.add(pname)
                    new_ops.append(_marker_op(
                        "send", {"X": [pname + "@GRAD"]},
                        {"Out": []},
                        {"endpoints": [self._param_to_ep[pname]],
                         OP_ROLE_KEY: OpRole.RPC}))
                continue
            new_ops.append(op)
        # recv updated params after the send barrier
        for pname, ep in self._param_to_ep.items():
            new_ops.append(_marker_op(
                "recv", {}, {"Out": [pname]},
                {"endpoints": [ep], OP_ROLE_KEY: OpRole.RPC}))
        block.ops = new_ops
        trainer._bump_version()
        return trainer

    def get_pserver_program(self, endpoint):
        """One optimizer sub-block per owned param under a listen_and_serv
        root (reference: get_pserver_program:674)."""
        from paddle_tpu.framework import Program

        pserver = Program()
        # copy global vars the optimizer ops touch
        src_block = self.origin_program.desc.global_block()
        dst_block = pserver.desc.global_block()
        owned = [p for p, ep in self._param_to_ep.items() if ep == endpoint]
        opt_blocks = []
        for pname in owned:
            ops = self._ops_for_param(pname)
            sub = pserver.desc.append_block(0)
            for op in ops:
                sub.ops.append(_clone_op(op))
                for n in op.input_arg_names() + op.output_arg_names():
                    vd = src_block.find_var_recursive(n)
                    if vd is not None and n not in dst_block.vars:
                        import copy

                        dst_block.vars[n] = copy.deepcopy(vd)
            opt_blocks.append(sub.idx)
        dst_block.ops.append(_marker_op(
            "listen_and_serv", {}, {},
            {"endpoint": endpoint,
             "optimize_blocks": opt_blocks,
             "Fanin": self.trainer_num,
             "sync_mode": self.sync_mode,
             OP_ROLE_KEY: OpRole.RPC}))
        pserver._bump_version()
        pserver.blocks = pserver.blocks[:1]
        from paddle_tpu.framework import Block

        pserver.blocks = [Block.__new__(Block)]
        b = pserver.blocks[0]
        b.program = pserver
        b.desc = dst_block
        b.idx = 0
        b.ops = []
        b.vars = {}
        return pserver

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        """Pserver startup: initialize only the owned params' state
        (reference: get_startup_program:927)."""
        return self.origin_startup

    def get_pserver_programs(self, endpoint):
        return (self.get_pserver_program(endpoint),
                self.get_startup_program(endpoint))


def _marker_op(type_, inputs, outputs, attrs):
    from paddle_tpu.core.desc import OpDesc

    return OpDesc(type_, inputs, outputs, attrs)


def _clone_op(op):
    from paddle_tpu.core.desc import OpDesc

    return OpDesc(op.type, {k: list(v) for k, v in op.inputs.items()},
                  {k: list(v) for k, v in op.outputs.items()},
                  dict(op.attrs))
