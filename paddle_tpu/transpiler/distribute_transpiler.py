"""DistributeTranspiler (reference:
python/paddle/fluid/transpiler/distribute_transpiler.py:161).

The reference rewrites programs for two transports: parameter-server
(param blocks sliced across pservers, trainer send/recv + barriers,
listen_and_serv optimizer blocks — :280-952) and collective "nccl2"
(:226-244, gen_nccl_id bootstrap). TPU-native:

* collective mode needs NO program rewriting — the multi-host collective is
  the SAME compiled program over a DCN-spanning mesh; `transpile` wires the
  coordinator env (paddle_tpu.parallel.env.init_distributed plays
  gen_nccl_id) and `get_trainer_program` returns the program unchanged.
* "mesh" mode (config.mode = "mesh") supersedes both for dense models:
  the program is returned unchanged and run under a jax mesh
  (Executor.run(mesh=...) / PADDLE_TPU_MESH) — gradient all-reduce is an
  in-graph psum XLA derives from the sharding specs, not an RPC.
* pserver mode is reproduced structurally: params are round-robin assigned
  to pserver endpoints, the pserver program gets one optimizer sub-block
  per owned param (the listen_and_serv body), and the trainer program's
  optimizer ops for remote params are replaced by send/recv markers. The
  live RPC transport rides the host parameter service (see
  paddle_tpu.distributed; in-process execution of both programs is fully
  functional for tests, matching the reference's
  multi-process-on-localhost test topology).
"""

from paddle_tpu.core.desc import VarDescData
from paddle_tpu.framework import OP_ROLE_KEY, OpRole


class DistributeTranspilerConfig:
    """(reference: distribute_transpiler.py:130)"""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    mode = "pserver"

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192


class RoundRobin:
    """(reference: ps_dispatcher.py RoundRobin)"""

    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._i = 0

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._i % len(self._eps)])
            self._i += 1
        return out

    def reset(self):
        self._i = 0


class HashName:
    """(reference: ps_dispatcher.py HashName) — stable hash of the var
    name picks the endpoint, so re-transpiles agree without shared
    state."""

    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)

    @staticmethod
    def _hash_block(entry, total):
        import zlib

        name = entry[1] or entry[0] if isinstance(entry, tuple) else entry
        return zlib.crc32(str(name).encode("utf-8")) % total

    def dispatch(self, varlist):
        return [self._eps[self._hash_block(v, len(self._eps))]
                for v in varlist]

    def reset(self):
        pass


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._mode = None
        self._param_to_ep = {}
        self._param_blocks = {}

    # -- entry point -------------------------------------------------------
    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None, current_endpoint=""):
        from paddle_tpu.framework import default_main_program

        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.origin_program = program or default_main_program()
        self.origin_startup = startup_program
        self._dist_tables = {}

        if self.config.mode == "mesh":
            # GSPMD mode: no program rewriting AND no RPC transport —
            # gradient reduction is an in-graph psum under the mesh's dp
            # axis, derived by XLA's partitioner when the unchanged
            # program is run with a mesh (Executor.run(mesh=...),
            # ParallelExecutor(dist_strategy="mesh"), or the
            # PADDLE_TPU_MESH flag). The transpiler only validates that
            # no pserver-specific feature was requested.
            self._mode = "mesh"
            self._endpoints = []
            block = self.origin_program.desc.global_block()
            dist_tables = [
                op.inputs["W"][0] for op in block.ops
                if op.type == "lookup_table"
                and op.attrs.get("is_distributed", False)]
            if dist_tables:
                raise NotImplementedError(
                    "distributed lookup tables %s need the pserver "
                    "transport; mesh mode shards dense state only"
                    % sorted(set(dist_tables)))
            return

        if isinstance(trainers, str) or self.config.mode == "nccl2":
            # collective mode: endpoints string in `trainers`
            self._mode = "collective"
            self._endpoints = (
                trainers.split(",") if isinstance(trainers, str) else [])
            return

        self._mode = "pserver"
        self.pserver_endpoints = [p for p in pservers.split(",") if p]

        # Distributed lookup tables (reference:
        # distribute_lookup_table.py:56 find_distributed_lookup_table):
        # embedding params marked is_distributed are row-sharded across ALL
        # pservers with runtime prefetch, not round-robin-assigned whole.
        self._dist_tables = {}
        block = self.origin_program.desc.global_block()
        for op in block.ops:
            if (op.type == "lookup_table"
                    and op.attrs.get("is_distributed", False)):
                wname = op.inputs["W"][0]
                vd = block.find_var_recursive(wname)
                vocab, dim = int(vd.shape[0]), int(vd.shape[1])
                self._dist_tables[wname] = {
                    "vocab": vocab,
                    "dim": dim,
                    "padding_idx": op.attrs.get("padding_idx", -1),
                    "shards": self._shard_ranges(vocab),
                }

        dispatcher = (self.config.split_method or RoundRobin)(
            self.pserver_endpoints)
        params = [
            p for p in self.origin_program.all_parameters()
            if p.name not in self._dist_tables
        ]
        # slice_var_up (reference: distribute_transpiler.py slice_variable
        # :130-152): split each param into >=min_block_size-element blocks
        # aligned on dim 0, round-robin the BLOCKS over pservers so one
        # big embedding doesn't pin a single server. self._param_blocks:
        # pname -> [(block_name, row_start, row_end, endpoint)], only for
        # params actually split (whole-var params stay in _param_to_ep).
        self._param_blocks = {}
        dispatch_units = []      # (pname, block_name_or_None, rows)
        for p in params:
            blocks = self._slice_rows(p)
            if blocks is None:
                dispatch_units.append((p.name, None, None))
            else:
                for bi, (r0, r1) in enumerate(blocks):
                    dispatch_units.append(
                        (p.name, "%s.block%d" % (p.name, bi), (r0, r1)))
        eps = dispatcher.dispatch(dispatch_units)
        self._param_to_ep = {}
        for (pname, bname, rows), ep in zip(dispatch_units, eps):
            if bname is None:
                self._param_to_ep[pname] = ep
            else:
                self._param_blocks.setdefault(pname, []).append(
                    (bname, rows[0], rows[1], ep))

    def _slice_rows(self, param):
        """Row ranges per block, or None when the param stays whole
        (reference slice_variable's numel/min_block_size formula, dim-0
        aligned)."""
        import math

        if not self.config.slice_var_up:
            return None
        shape = list(param.shape or [])
        if len(shape) == 0:
            return None
        numel = 1
        for d in shape:
            numel *= int(d)
        slice_count = len(self.pserver_endpoints)
        max_count = max(int(numel // float(self.config.min_block_size)), 1)
        split_count = min(max_count, slice_count)
        if split_count <= 1:
            return None
        dim1 = max(numel // int(shape[0]), 1)
        block_size = int(math.ceil(numel / float(split_count)))
        remains = block_size % dim1
        if remains != 0:
            block_size += dim1 - remains
        rows_per = block_size // dim1
        out = []
        r = 0
        while r < int(shape[0]):
            out.append((r, min(r + rows_per, int(shape[0]))))
            r += rows_per
        return out if len(out) > 1 else None

    def _shard_ranges(self, vocab):
        """Contiguous row ranges per pserver (reference splits by blocks via
        split_ids_op's mod sharding; contiguous keeps gathers local)."""
        n = len(self.pserver_endpoints)
        per = (vocab + n - 1) // n
        out = []
        for i, ep in enumerate(self.pserver_endpoints):
            start = min(i * per, vocab)
            end = min(start + per, vocab)
            out.append((ep, start, end))
        return out

    # -- collective / mesh -------------------------------------------------
    def get_trainer_program(self, wait_port=True):
        if self._mode in ("collective", "mesh"):
            return self.origin_program
        return self._build_trainer_program()

    # -- pserver -----------------------------------------------------------
    def _ops_for_param(self, pname):
        """Optimizer-role ops whose op_role_var mentions the param."""
        block = self.origin_program.desc.global_block()
        out = []
        for op in block.ops:
            role = int(op.attrs.get(OP_ROLE_KEY, 0))
            if not role & OpRole.Optimize:
                continue
            rv = op.attrs.get("op_role_var", [])
            if any(v == pname or v == pname + "@GRAD" for v in rv):
                out.append(op)
        return out

    def _build_trainer_program(self):
        """Trainer keeps forward+backward; optimizer ops for params owned by
        remote pservers are replaced by send/recv markers (reference:
        get_trainer_program:554). Distributed lookup tables additionally
        have their lookup/grad ops swapped for the prefetch pair
        (reference: distribute_lookup_table.py — the trainer never holds
        the table; DistTrainer does the prefetch/sparse-send RPC)."""
        trainer = self.origin_program.clone()
        block = trainer.desc.global_block()
        remote_params = set(self._param_to_ep) | set(self._param_blocks)
        new_ops = []
        sent = set()
        # per-lookup prefetch vars: a table looked up twice (shared-vocab
        # CTR embeddings) gets distinct prefetch/grad vars per lookup site
        self._pref_by_out = {}
        self._pref_count = {}
        for op in block.ops:
            role = int(op.attrs.get(OP_ROLE_KEY, 0))
            rv = op.attrs.get("op_role_var", [])
            owned = [v for v in rv if v in remote_params]
            if role & OpRole.Optimize and owned:
                pname = owned[0]
                if pname not in sent:
                    sent.add(pname)
                    if pname in self._param_blocks:
                        # one send per block: the trainer slices the grad
                        # rows (reference: send_op splitting VarBlocks)
                        for bname, r0, r1, ep in self._param_blocks[pname]:
                            new_ops.append(_marker_op(
                                "send", {"X": [pname + "@GRAD"]},
                                {"Out": []},
                                {"endpoints": [ep],
                                 "wire": bname + "@GRAD",
                                 "rows": [r0, r1],
                                 OP_ROLE_KEY: OpRole.RPC}))
                    else:
                        new_ops.append(_marker_op(
                            "send", {"X": [pname + "@GRAD"]},
                            {"Out": []},
                            {"endpoints": [self._param_to_ep[pname]],
                             OP_ROLE_KEY: OpRole.RPC}))
                continue
            if self._dist_tables:
                if (role & OpRole.Optimize
                        and any(v in self._dist_tables for v in rv)):
                    continue  # table updates happen on the shard owners
                if (op.type == "lookup_table"
                        and op.inputs["W"][0] in self._dist_tables):
                    new_ops.append(self._rewrite_dist_lookup(block, op))
                    continue
                if (op.type == "lookup_table_grad"
                        and op.inputs["W"][0] in self._dist_tables):
                    new_ops.append(self._rewrite_dist_lookup_grad(block, op))
                    continue
            new_ops.append(op)
        # recv updated params after the send barrier
        for pname, ep in self._param_to_ep.items():
            new_ops.append(_marker_op(
                "recv", {}, {"Out": [pname]},
                {"endpoints": [ep], OP_ROLE_KEY: OpRole.RPC}))
        for pname, blocks in self._param_blocks.items():
            for bname, r0, r1, ep in blocks:
                new_ops.append(_marker_op(
                    "recv", {}, {"Out": [pname]},
                    {"endpoints": [ep], "wire": bname,
                     "rows": [r0, r1], OP_ROLE_KEY: OpRole.RPC}))
        # The rewritten grad ops no longer produce the table's @GRAD
        # contribution vars. Backward's dedup `sum` over them is dropped;
        # any OTHER surviving consumer (gradient clip / regularization on
        # the table) has no gradient to read — fail loudly rather than
        # miscompute (the reference likewise keeps the distributed table
        # out of clip/regularization, distribute_lookup_table.py).
        if self._dist_tables:
            dangling = set()
            for wname in self._dist_tables:
                dangling.add(wname + "@GRAD")
                for vn in block.vars:
                    if vn.startswith(wname + "@GRAD@"):
                        dangling.add(vn)
            kept = []
            for op in new_ops:
                ins = set(op.input_arg_names())
                outs = set(op.output_arg_names())
                if (op.type == "sum" and outs and outs <= dangling
                        and ins <= dangling):
                    continue
                hit = ins & dangling
                if hit:
                    raise NotImplementedError(
                        "op %r consumes gradient %s of a distributed "
                        "lookup table; gradient clip/regularization on a "
                        "distributed table is not supported" %
                        (op.type, sorted(hit)))
                kept.append(op)
            new_ops = kept
        block.ops = new_ops
        # the table itself no longer exists trainer-side
        for wname in self._dist_tables:
            block.vars.pop(wname, None)
        trainer._bump_version()
        return trainer

    def table_state_var_names(self):
        """Names of each distributed table and its table-shaped optimizer
        state (Adam moments etc.) — state that lives only on shard owners
        and must never be materialized trainer-side."""
        src_block = self.origin_program.desc.global_block()
        out = set()
        for wname, info in self._dist_tables.items():
            out.add(wname)
            for op in self._ops_for_param(wname):
                for n in op.input_arg_names() + op.output_arg_names():
                    vd = src_block.find_var_recursive(n)
                    if (vd is not None and vd.shape is not None
                            and list(vd.shape) == [info["vocab"],
                                                   info["dim"]]):
                        out.add(n)
        return out

    def _new_prefetch_var(self, wname):
        k = self._pref_count.get(wname, 0)
        self._pref_count[wname] = k + 1
        return "%s@PREFETCH.%d" % (wname, k)

    def _ensure_var(self, block, name, shape):
        if name not in block.vars:
            block.vars[name] = VarDescData(name, shape=shape)

    def _rewrite_dist_lookup(self, block, op):
        wname = op.inputs["W"][0]
        info = self._dist_tables[wname]
        pref = self._new_prefetch_var(wname)
        self._pref_by_out[op.outputs["Out"][0]] = pref
        self._ensure_var(block, pref, [None, info["dim"]])
        return _marker_op(
            "distributed_lookup",
            {"Prefetched": [pref], "Ids": list(op.inputs["Ids"])},
            {"Out": list(op.outputs["Out"])},
            # per-site padding_idx: two lookups of one table may differ
            {"padding_idx": op.attrs.get("padding_idx", -1),
             "table_name": wname,
             OP_ROLE_KEY: int(op.attrs.get(OP_ROLE_KEY, 0))})

    def _rewrite_dist_lookup_grad(self, block, op):
        wname = op.inputs["W"][0]
        info = self._dist_tables[wname]
        # the grad op's Out@GRAD names the forward output's grad var;
        # strip the suffix to find which lookup site this differentiates
        og = op.inputs["Out@GRAD"][0]
        from paddle_tpu.framework import grad_var_name

        out_name = og[:-len("@GRAD")] if og.endswith("@GRAD") else og
        pref = self._pref_by_out[out_name]
        gname = grad_var_name(pref)
        self._ensure_var(block, gname, [None, info["dim"]])
        return _marker_op(
            "distributed_lookup_grad",
            {"Ids": list(op.inputs["Ids"]),
             "Out@GRAD": list(op.inputs.get("Out@GRAD", []))},
            {"Prefetched@GRAD": [gname]},
            {"padding_idx": op.attrs.get("padding_idx", -1),
             "table_name": wname,
             OP_ROLE_KEY: int(op.attrs.get(OP_ROLE_KEY, 0))})

    def get_trainer_startup_program(self):
        """Trainer startup without the distributed tables' init — trainers
        must never materialize the full table (reference:
        distribute_transpiler delete_ops on the table init)."""
        if self.origin_startup is None or not self._dist_tables:
            return self.origin_startup
        drop = self.table_state_var_names()
        startup = self.origin_startup.clone()
        block = startup.desc.global_block()
        block.ops = [
            op for op in block.ops
            if not any(n in drop for n in op.output_arg_names())
        ]
        for n in drop:
            block.vars.pop(n, None)
        startup._bump_version()
        return startup

    def get_pserver_program(self, endpoint):
        """One optimizer sub-block per owned param under a listen_and_serv
        root (reference: get_pserver_program:674)."""
        from paddle_tpu.framework import Program

        pserver = Program()
        # copy global vars the optimizer ops touch
        src_block = self.origin_program.desc.global_block()
        dst_block = pserver.desc.global_block()
        owned = [p for p, ep in self._param_to_ep.items() if ep == endpoint]
        opt_blocks = []
        block_grads = []   # grad var consumed by each block (async routing)
        for pname in owned:
            ops = self._ops_for_param(pname)
            sub = pserver.desc.append_block(0)
            _clone_ops_into(sub, ops, src_block, dst_block)
            opt_blocks.append(sub.idx)
            block_grads.append(pname + "@GRAD")

        # sliced params: one optimizer sub-block PER OWNED BLOCK, with the
        # param/grad/state vars renamed to block-unique names and
        # re-declared at the block's row count (reference:
        # _create_vars_from_blocklist + the per-block optimize blocks of
        # get_pserver_program:674; state slicing like _get_optimizer_input)
        sliced_blocks_attr = []
        prune_full = set()   # full-shape originals superseded by renames
        for pname, blocks in self._param_blocks.items():
            pd = src_block.find_var_recursive(pname)
            pshape = list(pd.shape)
            ops = self._ops_for_param(pname)
            for bname, r0, r1, ep in blocks:
                if ep != endpoint:
                    continue
                sub = pserver.desc.append_block(0)
                _clone_ops_into(sub, ops, src_block, dst_block)
                # rename every var the block WRITES (plus param + grad) so
                # two blocks of one param on this server never collide;
                # param-shaped renames also get the block's row count
                written = {pname, pname + "@GRAD"}
                for op in ops:
                    written.update(op.output_arg_names())
                suffix = bname[len(pname):]          # ".block%d"
                rename = {n: n + suffix for n in written
                          if src_block.find_var_recursive(n) is not None}
                rename[pname] = bname
                rename[pname + "@GRAD"] = bname + "@GRAD"
                for op in sub.ops:
                    for slot, names in op.inputs.items():
                        op.inputs[slot] = [rename.get(n, n) for n in names]
                    for slot, names in op.outputs.items():
                        op.outputs[slot] = [rename.get(n, n)
                                            for n in names]
                import copy as _copy

                for old, new in rename.items():
                    vd = dst_block.vars.get(old) or \
                        src_block.find_var_recursive(old)
                    nd = _copy.deepcopy(vd)
                    nd.name = new
                    if nd.shape is not None and list(nd.shape) == pshape:
                        nd.shape = [r1 - r0] + pshape[1:]
                    dst_block.vars[new] = nd
                sliced_blocks_attr.append({
                    "param": pname, "name": bname, "rows": [r0, r1],
                    "rename": dict(rename), "block": sub.idx,
                })
                opt_blocks.append(sub.idx)
                block_grads.append(bname + "@GRAD")
                prune_full.update(old for old, new in rename.items()
                                  if old != new)
        # drop the full-shape descs _clone_ops_into copied — no op on this
        # server references them after renaming, and a declared full-size
        # param would contradict the never-holds-the-whole-var contract
        for old in prune_full:
            dst_block.vars.pop(old, None)

        # Distributed lookup tables: every pserver owns one row-shard of
        # every table. The optimizer sub-block is the ORIGINAL optimizer op
        # fed by make_selected_rows assembling the wire (rows, values) into
        # a SelectedRows grad; table-shaped vars are re-declared at shard
        # shape (reference: the table optimize block of
        # distribute_transpiler.py:952 _create_table_optimize_block).
        dist_tables_attr = []
        for wname, info in self._dist_tables.items():
            shard = [s for s in info["shards"] if s[0] == endpoint]
            if not shard:
                continue
            _, start, end = shard[0]
            shard_rows = end - start
            ops = self._ops_for_param(wname)
            sub = pserver.desc.append_block(0)
            rows_v, vals_v = wname + "@GRAD@ROWS", wname + "@GRAD@VALUES"
            sub.ops.append(_marker_op(
                "make_selected_rows",
                {"Rows": [rows_v], "Values": [vals_v]},
                {"Out": [wname + "@GRAD"]},
                {"height": shard_rows, OP_ROLE_KEY: OpRole.Optimize}))
            dst_block.vars[rows_v] = VarDescData(rows_v, dtype="int64")
            dst_block.vars[vals_v] = VarDescData(vals_v)
            touched = _clone_ops_into(sub, ops, src_block, dst_block)
            # re-declare table-shaped state at shard shape
            sliced = set()
            for n in touched:
                nd = dst_block.vars[n]
                if (nd.shape is not None
                        and list(nd.shape) == [info["vocab"], info["dim"]]):
                    nd.shape = [shard_rows, info["dim"]]
                    sliced.add(n)
            dist_tables_attr.append({
                "name": wname, "start": start, "end": end,
                "vocab": info["vocab"], "block": sub.idx,
                "sliced": sorted(sliced),
            })
            opt_blocks.append(sub.idx)
            block_grads.append(wname + "@GRAD")

        dst_block.ops.append(_marker_op(
            "listen_and_serv", {}, {},
            {"endpoint": endpoint,
             "optimize_blocks": opt_blocks,
             "block_grads": block_grads,
             "Fanin": self.trainer_num,
             "sync_mode": self.sync_mode,
             "dist_tables": dist_tables_attr,
             "sliced_blocks": sliced_blocks_attr,
             OP_ROLE_KEY: OpRole.RPC}))
        pserver._bump_version()
        pserver.blocks = pserver.blocks[:1]
        from paddle_tpu.framework import Block

        pserver.blocks = [Block.__new__(Block)]
        b = pserver.blocks[0]
        b.program = pserver
        b.desc = dst_block
        b.idx = 0
        b.ops = []
        b.vars = {}
        return pserver

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        """Pserver startup. For distributed lookup tables the init ops of
        table-shaped vars are rewritten to this endpoint's SHARD shape so
        no server ever materializes the whole table — the memory contract
        the sharding exists for (reference: get_startup_program:927 slices
        param init blocks the same way)."""
        base = startup_program or self.origin_startup
        if base is None or endpoint is None or not (
                self._dist_tables or self._param_blocks):
            return base
        if pserver_program is None:
            pserver_program = self.get_pserver_program(endpoint)
        lns = pserver_program.desc.global_block().ops[-1]
        resize = {}  # var -> shard rows
        for d in lns.attrs.get("dist_tables", []):
            for n in d["sliced"]:
                resize[n] = d["end"] - d["start"]
        startup = base.clone()
        block = startup.desc.global_block()
        # sliced param blocks: clone each renamed var's init op at the
        # block's row count and drop the full-var init (reference:
        # get_startup_program:927 slicing param init blocks)
        sliced = lns.attrs.get("sliced_blocks", [])
        drop_full = set()
        new_ops = []
        for d in sliced:
            r0, r1 = d["rows"]
            pd = self.origin_program.desc.global_block() \
                .find_var_recursive(d["param"])
            pshape = list(pd.shape)
            for old, new in d["rename"].items():
                drop_full.add(old)
                for op in block.ops:
                    if old in op.output_arg_names():
                        clone = _clone_op(op)
                        for slot, names in clone.outputs.items():
                            clone.outputs[slot] = [
                                new if n == old else n for n in names]
                        if "shape" in clone.attrs and list(
                                clone.attrs["shape"]) == pshape:
                            shp = list(clone.attrs["shape"])
                            shp[0] = r1 - r0
                            clone.attrs["shape"] = shp
                        new_ops.append(clone)
                        vd = block.vars.get(old)
                        if vd is not None:
                            import copy as _copy

                            nd = _copy.deepcopy(vd)
                            nd.name = new
                            if (nd.shape is not None
                                    and list(nd.shape) == pshape):
                                nd.shape = [r1 - r0] + pshape[1:]
                            block.vars[new] = nd
        if sliced:
            block.ops = [
                op for op in block.ops
                if not (set(op.output_arg_names()) & drop_full)
            ] + new_ops
        for op in block.ops:
            for n in op.output_arg_names():
                if n in resize and "shape" in op.attrs:
                    shape = list(op.attrs["shape"])
                    shape[0] = resize[n]
                    op.attrs["shape"] = shape
        for n, rows in resize.items():
            vd = block.vars.get(n)
            if vd is not None and vd.shape:
                vd.shape = [rows] + list(vd.shape[1:])
        startup._bump_version()
        return startup

    def get_pserver_programs(self, endpoint):
        pserver = self.get_pserver_program(endpoint)
        return (pserver, self.get_startup_program(endpoint, pserver))


def _clone_ops_into(sub, ops, src_block, dst_block):
    """Clone ops into a pserver sub-block, copying the var descs they
    touch into the root block; returns the touched var names."""
    import copy

    touched = []
    for op in ops:
        sub.ops.append(_clone_op(op))
        for n in op.input_arg_names() + op.output_arg_names():
            vd = src_block.find_var_recursive(n)
            if vd is None:
                continue
            if n not in dst_block.vars:
                dst_block.vars[n] = copy.deepcopy(vd)
            touched.append(n)
    return touched


def _marker_op(type_, inputs, outputs, attrs):
    from paddle_tpu.core.desc import OpDesc

    return OpDesc(type_, inputs, outputs, attrs)


def _clone_op(op):
    from paddle_tpu.core.desc import OpDesc

    return OpDesc(op.type, {k: list(v) for k, v in op.inputs.items()},
                  {k: list(v) for k, v in op.outputs.items()},
                  dict(op.attrs))
