"""InferenceTranspiler: inference-time program rewrites (reference:
python/paddle/fluid/transpiler/inference_transpiler.py — BN fold into the
preceding conv, conv+eltwise_add fusion). XLA fuses elementwise chains
automatically; the numerically-material rewrite — folding frozen
batch-norm statistics into conv weights — is done here because it removes
the BN state vars entirely."""

import numpy as np


class InferenceTranspiler:
    def transpile(self, program, place=None, scope=None):
        from paddle_tpu.executor import global_scope

        scope = scope or global_scope()
        self._fold_batch_norms(program, scope)
        return program

    def _fold_batch_norms(self, program, scope):
        """conv2d (no act) directly followed by batch_norm in test mode →
        scale conv filters/bias by gamma/sqrt(var+eps), fold mean/beta into
        bias (reference: inference_transpiler.py fuse_batch_norm)."""
        block = program.desc.global_block()
        ops = block.ops
        for i in range(len(ops) - 1):
            conv, bn = ops[i], ops[i + 1]
            if conv.type != "conv2d" or bn.type != "batch_norm":
                continue
            if conv.outputs.get("Output", [None])[0] != \
                    bn.inputs.get("X", [None])[0]:
                continue
            # Folding is only sound when: BN runs with frozen statistics
            # (test mode), the conv output feeds ONLY this BN (otherwise
            # other consumers would see rescaled activations), and
            # groups==1 (grouped conv filters don't map 1:1 onto output
            # channels for the per-channel rescale below).
            if not bn.attrs.get("is_test", False):
                continue
            if int(conv.attrs.get("groups", 1)) != 1:
                continue
            conv_out_name = conv.outputs["Output"][0]
            consumers = sum(
                1 for op in ops
                for names in op.inputs.values()
                for n in names if n == conv_out_name)
            if consumers != 1:
                continue
            w_name = conv.inputs["Filter"][0]
            w = np.asarray(scope.get(w_name))
            gamma = np.asarray(scope.get(bn.inputs["Scale"][0]))
            beta = np.asarray(scope.get(bn.inputs["Bias"][0]))
            mean = np.asarray(scope.get(bn.inputs["Mean"][0]))
            var = np.asarray(scope.get(bn.inputs["Variance"][0]))
            eps = float(bn.attrs.get("epsilon", 1e-5))

            inv_std = 1.0 / np.sqrt(var + eps)
            scale = (gamma * inv_std).astype(w.dtype)
            scope.set(w_name, w * scale.reshape(-1, 1, 1, 1))
            bias_fold = (beta - gamma * mean * inv_std).astype(w.dtype)

            # Rewire: conv now writes a fresh intermediate var (its
            # activations are rescaled, so the original output name must
            # NOT keep existing with changed values — a fetch of it fails
            # loudly instead of silently returning rescaled data), then an
            # elementwise bias produces BN's output.
            bn_out = bn.outputs["Y"][0]
            bias_name = w_name + ".bn_bias"
            from paddle_tpu.core.desc import OpDesc, VarDescData

            if bias_name not in block.vars:
                block.vars[bias_name] = VarDescData(
                    bias_name, shape=[int(bias_fold.shape[0])],
                    dtype="float32", persistable=True)
            scope.set(bias_name, bias_fold)
            folded_out = conv_out_name + ".bnfold"
            if folded_out not in block.vars and conv_out_name in block.vars:
                src = block.vars[conv_out_name]
                block.vars[folded_out] = VarDescData(
                    folded_out, shape=list(src.shape or []),
                    dtype=src.dtype, persistable=False)
            conv.outputs["Output"] = [folded_out]
            ops[i + 1] = OpDesc(
                "elementwise_add",
                inputs={"X": [folded_out], "Y": [bias_name]},
                outputs={"Out": [bn_out]},
                attrs={"axis": 1},
            )
        program._bump_version()
