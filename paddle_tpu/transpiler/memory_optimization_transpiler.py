"""Memory-optimization transpiler API (reference:
python/paddle/fluid/transpiler/memory_optimization_transpiler.py).

The reference rewrites the program to reuse variable buffers by lifetime
analysis. Under whole-block XLA compilation, buffer liveness/reuse is the
compiler's job (XLA's buffer assignment already performs this analysis on
the fused program), so these are intentional no-ops kept for script
compatibility; `skip_opt_set` etc. are accepted."""


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    return input_program


def release_memory(input_program, skip_opt_set=None):
    return input_program
