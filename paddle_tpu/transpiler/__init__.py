"""Program transpilers (reference: python/paddle/fluid/transpiler/)."""

from paddle_tpu.transpiler.distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
    HashName,
    RoundRobin,
)
from paddle_tpu.transpiler.inference_transpiler import (  # noqa: F401
    InferenceTranspiler,
)
from paddle_tpu.transpiler.memory_optimization_transpiler import (  # noqa: F401
    memory_optimize,
    release_memory,
)
