"""Flash attention as Pallas TPU kernels, forward AND backward.

Forward streams K/V blocks from VMEM against a resident Q block with
online-softmax accumulation and emits the per-row logsumexp — O(T) memory,
MXU-shaped contractions (the kernel the reference implements as
math/softmax.cu + matmuls, fused here instead).

Backward is the FlashAttention-2 decomposition: a cheap XLA delta
precompute (rowsum(dO*O)), a dQ kernel (Q block resident, K/V streamed)
and a dK/dV kernel (K/V block resident, Q streamed), all re-deriving the
softmax from the saved logsumexp instead of materializing the [T, T]
probability matrix. The plain-XLA recompute path remains the fallback
(PADDLE_TPU_FLASH_BWD=xla, or shapes the kernels cannot tile).

``fused_attention`` is the dispatch point: the Pallas kernel on TPU (or in
interpreter mode for tests), the plain-XLA composition elsewhere.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, causal,
                 scale, block_q):
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    j = pl.program_id(1)
    T = k_ref.shape[1]
    nk = T // block_k

    q_pos = j * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(s, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(s * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(s * block_k, block_k), :].astype(jnp.float32)
        sij = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = s * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            sij = jnp.where(q_pos >= k_pos, sij, _NEG)
        m_new = jnp.maximum(m, jnp.max(sij, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(sij - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, q_ref.shape[2]), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)

    if causal:
        # blocks fully above the diagonal contribute nothing — skip them
        nk_eff = jnp.minimum(
            nk, (j + 1) * block_q // block_k + (1 if block_q % block_k else 0)
        )
        nk_eff = jnp.maximum(nk_eff, 1)
    else:
        nk_eff = nk
    acc, m, l = jax.lax.fori_loop(0, nk_eff, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # logsumexp per row, the softmax residual the backward kernels re-derive
    # p from (FlashAttention-2's L)
    lse_ref[0] = (m + jnp.log(jnp.maximum(l, 1e-30))).reshape(block_q)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    B, H, T, D = q.shape
    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    grid = (B * H, T // block_q)

    kernel = functools.partial(
        _attn_kernel, block_k=block_k, causal=causal, scale=scale,
        block_q=block_q)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct(qr.shape, q.dtype),
            jax.ShapeDtypeStruct((B * H, T), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, T, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_q), lambda b, j: (b, j)),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, T, D), lse


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, block_k, causal, scale, block_q):
    q = q_ref[0].astype(jnp.float32)          # [block_q, D]
    do = do_ref[0].astype(jnp.float32)        # [block_q, D]
    lse = lse_ref[0].reshape(block_q, 1)      # [block_q, 1]
    delta = delta_ref[0].reshape(block_q, 1)  # [block_q, 1]
    j = pl.program_id(1)
    T = k_ref.shape[1]
    nk = T // block_k
    q_pos = j * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(s, dq):
        k_blk = k_ref[0, pl.ds(s * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(s * block_k, block_k), :].astype(jnp.float32)
        sij = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = s * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            sij = jnp.where(q_pos >= k_pos, sij, _NEG)
        p = jnp.exp(sij - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        nk_eff = jnp.minimum(
            nk, (j + 1) * block_q // block_k + (1 if block_q % block_k else 0))
        nk_eff = jnp.maximum(nk_eff, 1)
    else:
        nk_eff = nk
    dq0 = jnp.zeros((block_q, q_ref.shape[2]), jnp.float32)
    dq = jax.lax.fori_loop(0, nk_eff, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_k, causal, scale, block_q):
    k_blk = k_ref[0].astype(jnp.float32)       # [block_k, D]
    v_blk = v_ref[0].astype(jnp.float32)       # [block_k, D]
    s_idx = pl.program_id(1)
    T = q_ref.shape[1]
    nq = T // block_q
    k_pos = s_idx * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)

    def body(j, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(j * block_q, block_q)].reshape(block_q, 1)
        delta = delta_ref[0, pl.ds(j * block_q, block_q)].reshape(
            block_q, 1)
        sij = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            sij = jnp.where(q_pos >= k_pos, sij, _NEG)
        p = jnp.exp(sij - lse)                 # [block_q, block_k]
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # q blocks strictly before this k block's first row see none of it
        j0 = (s_idx * block_k) // block_q
    else:
        j0 = 0
    dk0 = jnp.zeros((block_k, k_ref.shape[2]), jnp.float32)
    dv0 = jnp.zeros((block_k, v_ref.shape[2]), jnp.float32)
    dk, dv = jax.lax.fori_loop(j0, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, scale, block_q, block_k,
                    interpret):
    B, H, T, D = q.shape
    qr, kr, vr = (x.reshape(B * H, T, D) for x in (q, k, v))
    do = g.reshape(B * H, T, D)
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    # delta = rowsum(dO * O): cheap elementwise, XLA fuses it
    delta = jnp.sum(
        do.astype(jnp.float32) * out.reshape(B * H, T, D).astype(
            jnp.float32), axis=-1)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, causal=causal,
                          scale=scale, block_q=block_q),
        out_shape=jax.ShapeDtypeStruct(qr.shape, q.dtype),
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, T, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_q), lambda b, j: (b, j)),
            pl.BlockSpec((1, block_q), lambda b, j: (b, j)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, j: (b, j, 0)),
        interpret=interpret,
    )(qr, kr, vr, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_k=block_k, causal=causal,
                          scale=scale, block_q=block_q),
        out_shape=[
            jax.ShapeDtypeStruct(kr.shape, k.dtype),
            jax.ShapeDtypeStruct(vr.shape, v.dtype),
        ],
        grid=(B * H, T // block_k),
        in_specs=[
            pl.BlockSpec((1, T, D), lambda b, s: (b, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, s: (b, s, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, s: (b, s, 0)),
            pl.BlockSpec((1, T, D), lambda b, s: (b, 0, 0)),
            pl.BlockSpec((1, T), lambda b, s: (b, 0)),
            pl.BlockSpec((1, T), lambda b, s: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, s: (b, s, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, s: (b, s, 0)),
        ],
        interpret=interpret,
    )(qr, kr, vr, do, lse, delta)

    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))


def _xla_attention(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128, interpret=False):
    """[B, H, T, D] attention via the Pallas kernel; T must divide by the
    block sizes (clamped to T)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    out, _ = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                            interpret)
    return out


def _use_xla_bwd():
    import os

    return os.environ.get("PADDLE_TPU_FLASH_BWD", "") == "xla"


def _fa_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    T = q.shape[2]
    bq, bk = min(block_q, T), min(block_k, T)
    if _use_xla_bwd() or T % bq or T % bk:
        # fallback: recompute attention in XLA (O(T^2) intermediates but
        # always correct for odd shapes)
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _xla_attention(q_, k_, v_, causal, scale_),
            q, k, v)
        return vjp(g)
    return _flash_backward(q, k, v, out, lse, g, causal, scale_, bq, bk,
                           interpret)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def fused_attention(q, k, v, causal=False, scale=None, force_pallas=None):
    """Pallas flash attention on TPU; plain-XLA composition elsewhere.
    ``force_pallas=True`` runs the kernel in interpreter mode off-TPU
    (tests)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    T = q.shape[2]
    use_pallas = force_pallas if force_pallas is not None else (
        _HAS_PLTPU and _on_tpu() and T % 128 == 0)
    if use_pallas:
        return flash_attention(q, k, v, causal, scale,
                               interpret=not _on_tpu())
    return _xla_attention(q, k, v, causal, scale)
