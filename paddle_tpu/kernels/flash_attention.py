"""Flash attention as Pallas TPU kernels, forward AND backward.

Forward streams K/V blocks from VMEM against a resident Q block with
online-softmax accumulation and emits the per-row logsumexp — O(T) memory,
MXU-shaped contractions (the kernel the reference implements as
math/softmax.cu + matmuls, fused here instead; fused-op strategy per
paddle/fluid/operators/fused/).

Backward is the FlashAttention-2 decomposition: a cheap XLA delta
precompute (rowsum(dO*O)), a dQ kernel (Q block resident, K/V streamed)
and a dK/dV kernel (K/V block resident, Q streamed), all re-deriving the
softmax from the saved logsumexp instead of materializing the [Tq, Tk]
probability matrix. The plain-XLA recompute path remains the fallback
(PADDLE_TPU_FLASH_BWD=xla, or shapes the kernels cannot tile).

Mosaic layout notes (what made round-2's kernels fail to lower on the
real chip): every block's last two dims must be (8, 128)-tileable or span
the full array dim. The logsumexp/delta residuals are therefore carried
rank-3 as ``[B*H, Tq, _LSE_LANES=1]`` — the trailing unit lane axis spans
its full array dim (legal the same way the D=64 head dim is), never as
rank-2 ``(1, block_q)`` blocks whose sublane dim is neither 8-divisible
nor full. jax's own kernel instead replicates the scalar across 128
lanes; both lower, the unit lane costs 128x less HBM.

Masking is TPU-first: key-padding masks are passed as per-sequence
*lengths* living in SMEM (scalar memory), not as [B, H, T, T] additive
tensors — the kernel compares against a key-position iota. Causal masking
is a static flag. Attention dropout runs *inside* the kernel using a
counter-based hash RNG (murmur3 finalizer over the global (batch, q, k)
coordinate), so the forward and both backward kernels regenerate the
identical mask from (seed, coords) with no [Tq, Tk] mask ever stored.

``fused_attention`` is the dispatch point: the Pallas kernel on TPU (or in
interpreter mode for tests), the plain-XLA composition elsewhere.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG = -1e30
# Lane width for the logsumexp/delta residuals, carried as rank-3
# [B*H, Tq, _LSE_LANES] so every block spans full array dims on the last
# axis (Mosaic-legal, like the D=64 head dim). 1 verifies on hardware and
# keeps the residuals O(B*H*T); jax's own kernel replicates to 128 lanes
# (MIN_BLOCK_SIZE), which also lowers but costs 128x the HBM.
_LSE_LANES = 1


def _smem_spec():
    if _HAS_PLTPU:
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.BlockSpec(memory_space=None)  # pragma: no cover


def _keep_mask(seed, b, q_pos, k_pos, t_k, rate):
    """Deterministic dropout keep-mask from the *global* (b, q, k)
    coordinate: murmur3 finalizer bits -> uniform [0,1) -> >= rate.
    Counter-based, so the dQ and dK/dV kernels reproduce the forward's
    mask exactly regardless of their different iteration orders.

    (Round-5 measured the in-kernel dropout at ~25% of whole-kernel time
    and tried a strip-hoisted 1-multiply variant of this hash: the
    overhead did NOT move — the cost is the unavoidable extra
    compare/select/scale vector ops on the [bq, bk] tile, not the hash
    arithmetic — so the stronger full-avalanche form stays.)"""
    from paddle_tpu.ops.common import hash_mix_bits, keep_threshold

    idx = (q_pos * t_k + k_pos).astype(jnp.uint32)
    h = hash_mix_bits(idx ^ (seed.astype(jnp.uint32)
                             + jnp.uint32(0x9E3779B9)
                             * (b + 1).astype(jnp.uint32)))
    return (h >> 8) >= keep_threshold(rate)


def _nk_limit(nk, causal_hi, length, block_k, masked, causal):
    """Number of K blocks that can contribute: min over the causal frontier
    and the valid-key frontier (both dynamic-friendly fori_loop bounds).

    ``causal_hi`` may be 0 or negative when the whole Q tile precedes the
    K range (a ring-attention step holding a future K/V block) — the loop
    then runs zero iterations and the row publishes lse ~= -1e30, which
    the cross-step logaddexp merge treats as "no contribution". The
    masked limit is >= 1 by construction (lengths are clamped upstream)."""
    nk_eff = nk
    if causal:
        nk_eff = jnp.clip(causal_hi, 0, nk)
    if masked:
        nk_eff = jnp.minimum(nk_eff, (length + block_k - 1) // block_k)
    return nk_eff


def _causal_blocks(q_off, k_off, j, block_q, block_k):
    """Dynamic count of K blocks at or before the causal frontier of Q
    block ``j``, with Q/K living at global offsets ``q_off``/``k_off``
    (SMEM scalars — the ring-attention caller passes shard*T). Floor
    division handles the fully-masked (negative) case."""
    return (q_off - k_off + (j + 1) * block_q - 1) // block_k + 1


def _attn_kernel(len_ref, seed_ref, off_ref, q_ref, k_ref, v_ref, o_ref,
                 lse_ref, acc_s, m_s, l_s, *, block_q, block_k, causal,
                 scale, rate, masked, t_k):
    """Online-softmax forward with K/V STREAMED over the innermost grid
    axis (grid = (B*H, Tq/block_q, Tk/block_k)) and the (acc, m, l)
    carry in VMEM scratch — VMEM bounded by the block sizes, not Tk
    (the resident-K/V form capped context at ~8k: seq-16384 overran the
    16MB scoped limit in this kernel by 768KB)."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    s = pl.program_id(2)
    ns = pl.num_programs(2)
    length = len_ref[b]
    seed = seed_ref[0]
    q_off, k_off = off_ref[0], off_ref[1]

    @pl.when(s == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)
        m_s[...] = jnp.full_like(m_s, _NEG)
        l_s[...] = jnp.zeros_like(l_s)

    causal_hi = _causal_blocks(q_off, k_off, j, block_q, block_k)
    nk_eff = _nk_limit(ns, causal_hi, length, block_k, masked, causal)

    @pl.when(s < nk_eff)
    def _step():
        q = q_ref[0]                           # [block_q, D], input dtype
        k_blk = k_ref[0]                       # [block_k, D]
        v_blk = v_ref[0]
        q_pos = j * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        sij = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        k_pos = s * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            sij = jnp.where(q_pos + q_off >= k_pos + k_off, sij, _NEG)
        if masked:
            sij = jnp.where(k_pos < length, sij, _NEG)
        m = m_s[...]
        m_new = jnp.maximum(m, jnp.max(sij, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(sij - m_new)
        l_s[...] = l_s[...] * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_s[...] = m_new
        if rate > 0.0:
            keep = _keep_mask(seed, b, q_pos, k_pos, t_k, rate)
            p_acc = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - rate))
        else:
            p_acc = p
        acc_s[...] = acc_s[...] * corr + jax.lax.dot_general(
            p_acc.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(s == ns - 1)
    def _emit():
        acc, m, l = acc_s[...], m_s[...], l_s[...]
        # a row with EVERY key masked keeps m at _NEG, making p = exp(0)
        # = 1 garbage — zero it so the row publishes out = 0,
        # lse ~= -1e30 (the "no contribution" value the ring merge
        # expects). Without this guard only block-aligned offsets would
        # be safe.
        l = jnp.where(m > 0.5 * _NEG, l, 0.0)
        acc = jnp.where(m > 0.5 * _NEG, acc, 0.0)
        o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # logsumexp per row, the softmax residual the backward kernels
        # re-derive p from (FlashAttention-2's L); replicated across the
        # lane dim so the block stays (8, 128)-tileable
        lse = m + jnp.log(jnp.maximum(l, 1e-30))  # [block_q, 1]
        lse_ref[0] = jnp.broadcast_to(lse, (block_q, _LSE_LANES))


def _stream_kvmap(block_q, block_k, causal, offsets):
    """Index map for K/V blocks streamed over the innermost grid axis of
    a (b, q-block, k-block) grid. For causal runs without (traced) ring
    offsets the fetch index clamps to the causal frontier so skipped
    steps re-fetch the block a live step needs (consecutive equal
    indices elide the copy); ring-step offsets keep the identity map —
    wasted fetches on skipped steps, never wrong."""
    if causal and offsets is None:
        def kvmap(b, j, s):
            return (b, jnp.minimum(s, ((j + 1) * block_q - 1) // block_k),
                    0)
    else:
        def kvmap(b, j, s):
            return (b, s, 0)
    return kvmap


def _require_pltpu(what):
    if not _HAS_PLTPU:
        raise RuntimeError(
            "flash %s needs pallas-TPU scratch support (pltpu "
            "unimportable here); use the XLA fallback (forward: the "
            "plain composition; backward: PADDLE_TPU_FLASH_BWD=xla)"
            % what)


def _offsets_arr(offsets):
    """[q_off, k_off] int32 SMEM scalars — the Q/K global base positions
    (ring-attention shard offsets); [0, 0] for ordinary full attention."""
    if offsets is None:
        return jnp.zeros((2,), jnp.int32)
    return jnp.asarray(offsets, jnp.int32).reshape(2)


def _flash_forward(q, k, v, seq_lens, offsets, seed, causal, scale, rate,
                   block_q, block_k, interpret):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    grid = (B * H, Tq // block_q)

    masked = seq_lens is not None
    if masked:
        lens = jnp.repeat(jnp.maximum(seq_lens.astype(jnp.int32), 1), H)
    else:
        lens = jnp.full((B * H,), Tk, jnp.int32)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1)

    _require_pltpu("forward")
    _kvmap = _stream_kvmap(block_q, block_k, causal, offsets)
    kernel = functools.partial(
        _attn_kernel, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, rate=rate, masked=masked, t_k=Tk)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct(qr.shape, q.dtype),
            jax.ShapeDtypeStruct((B * H, Tq, _LSE_LANES), jnp.float32),
        ],
        grid=grid + (Tk // block_k,),
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            _smem_spec(),
            pl.BlockSpec((1, block_q, D), lambda b, j, s: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), _kvmap),
            pl.BlockSpec((1, block_k, D), _kvmap),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, s: (b, j, 0)),
            pl.BlockSpec((1, block_q, _LSE_LANES),
                         lambda b, j, s: (b, j, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        interpret=interpret,
    )(lens, seed_arr, _offsets_arr(offsets), qr, kr, vr)
    return out.reshape(B, H, Tq, D), lse


def _bwd_dq_kernel(len_ref, seed_ref, off_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dq_ref, dq_acc, *, block_q, block_k,
                   causal, scale, rate, masked, t_k):
    """dQ with K/V streamed over the innermost grid axis and the dq
    accumulator in VMEM scratch (same restructure as the forward — the
    resident-K/V form's VMEM grew with Tk)."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    s = pl.program_id(2)
    ns = pl.num_programs(2)
    length = len_ref[b]
    seed = seed_ref[0]
    q_off, k_off = off_ref[0], off_ref[1]

    @pl.when(s == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    causal_hi = _causal_blocks(q_off, k_off, j, block_q, block_k)
    nk_eff = _nk_limit(ns, causal_hi, length, block_k, masked, causal)

    @pl.when(s < nk_eff)
    def _step():
        q = q_ref[0]                          # [block_q, D]
        do = do_ref[0]                        # [block_q, D]
        lse = lse_ref[0][:, :1]               # [block_q, 1]
        delta = delta_ref[0][:, :1]           # [block_q, 1]
        k_blk = k_ref[0]                      # [block_k, D]
        v_blk = v_ref[0]
        q_pos = j * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        sij = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        k_pos = s * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            sij = jnp.where(q_pos + q_off >= k_pos + k_off, sij, _NEG)
        if masked:
            sij = jnp.where(k_pos < length, sij, _NEG)
        # fully-masked rows carry lse ~= -1e30; exp(sij - lse) would
        # overflow to inf there — such rows contribute no gradient
        p = jnp.where(lse > 0.5 * _NEG, jnp.exp(sij - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if rate > 0.0:
            keep = _keep_mask(seed, b, q_pos, k_pos, t_k, rate)
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - rate))
        ds = p * (dp - delta) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(s == ns - 1)
    def _emit():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(len_ref, seed_ref, off_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                    block_q, block_k, causal, scale, rate, masked):
    """dK/dV with the Q dimension STREAMED over the innermost grid axis
    (grid = (B*H, Tk/block_k, Tq/block_q)) and f32 accumulation in VMEM
    scratch — the earlier form held full-length Q/dO/lse/delta resident
    per program, so its VMEM footprint grew linearly with Tq and capped
    trainable context at ~2-4k tokens (seq-4096+dropout exceeded the 16MB
    scoped limit by 672KB; seq-8192 by 8.75MB). TPU grids iterate
    sequentially, so the accumulator pattern (zero at j==0, emit at
    j==nq-1) is the standard one — cf. the public pallas flash kernel's
    block_q_major streaming (jax.experimental.pallas.ops.tpu)."""
    b = pl.program_id(0)
    s_idx = pl.program_id(1)
    j = pl.program_id(2)
    nq = pl.num_programs(2)
    t_k = dk_ref.shape[1] * pl.num_programs(1)
    length = len_ref[b]
    seed = seed_ref[0]
    q_off, k_off = off_ref[0], off_ref[1]

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def compute():
        k_blk = k_ref[0]                       # [block_k, D]
        v_blk = v_ref[0]                       # [block_k, D]
        q = q_ref[0]                           # [block_q, D]
        do = do_ref[0]                         # [block_q, D]
        lse = lse_ref[0][:, :1]                # [block_q, 1]
        delta = delta_ref[0][:, :1]            # [block_q, 1]
        sij = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        q_pos = j * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = s_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            sij = jnp.where(q_pos + q_off >= k_pos + k_off, sij, _NEG)
        if masked:
            sij = jnp.where(k_pos < length, sij, _NEG)
        # guard fully-masked rows (lse ~= -1e30) as in the dQ kernel
        p = jnp.where(lse > 0.5 * _NEG, jnp.exp(sij - lse),
                      0.0)                     # [block_q, block_k]
        if rate > 0.0:
            keep = _keep_mask(seed, b, q_pos, k_pos, t_k, rate)
            inv = 1.0 / (1.0 - rate)
            p_drop = jnp.where(keep, p, 0.0) * inv
        else:
            keep = None
            p_drop = p
        dv_acc[...] += jax.lax.dot_general(
            p_drop.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if keep is not None:
            dp = jnp.where(keep, dp, 0.0) * inv
        ds = p * (dp - delta) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # q blocks whose last global row is before this k block's first
        # see none of it — same frontier as the old fori j0, now a
        # skipped grid step
        pl.when((j + 1) * block_q - 1 + q_off
                >= s_idx * block_k + k_off)(compute)
    else:
        compute()

    @pl.when(j == nq - 1)
    def _emit():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, g_lse, seq_lens, offsets, seed,
                    causal, scale, rate, block_q, block_k, interpret,
                    dq_blocks=None, dkv_blocks=None):
    """``dq_blocks``/``dkv_blocks``: optional (block_q, block_k) overrides
    per backward kernel — the two have different residency patterns (dQ
    keeps the Q tile resident and streams K/V; dK/dV the reverse), so the
    block sweep tunes them independently (VERDICT r4 Next #4)."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    do = g.reshape(B * H, Tq, D)
    bq_dq, bk_dq = dq_blocks or (block_q, block_k)
    bq_kv, bk_kv = dkv_blocks or (block_q, block_k)
    bq_dq, bk_dq = min(bq_dq, Tq), min(bk_dq, Tk)
    bq_kv, bk_kv = min(bq_kv, Tq), min(bk_kv, Tk)

    masked = seq_lens is not None
    if masked:
        lens = jnp.repeat(jnp.maximum(seq_lens.astype(jnp.int32), 1), H)
    else:
        lens = jnp.full((B * H,), Tk, jnp.int32)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1)
    off_arr = _offsets_arr(offsets)

    # delta = rowsum(dO * O): cheap elementwise, XLA fuses it; replicated
    # across the lane dim like lse so its blocks stay Mosaic-tileable.
    # A cotangent on the published logsumexp (the ring-attention merge
    # differentiates through lse) folds in exactly: d s from g_lse is
    # p * g_lse, and ds = p * (dp - delta + g_lse) — so delta -= g_lse.
    delta = jnp.sum(
        do.astype(jnp.float32) * out.reshape(B * H, Tq, D).astype(
            jnp.float32), axis=-1)
    if g_lse is not None:
        delta = delta - g_lse.reshape(B * H, Tq).astype(jnp.float32)
    delta = jnp.broadcast_to(delta[..., None], (B * H, Tq, _LSE_LANES))

    _require_pltpu("backward")
    _kvmap_dq = _stream_kvmap(bq_dq, bk_dq, causal, offsets)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=bq_dq, block_k=bk_dq,
                          causal=causal, scale=scale, rate=rate,
                          masked=masked, t_k=Tk),
        out_shape=jax.ShapeDtypeStruct(qr.shape, q.dtype),
        grid=(B * H, Tq // bq_dq, Tk // bk_dq),
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            _smem_spec(),
            pl.BlockSpec((1, bq_dq, D), lambda b, j, s: (b, j, 0)),
            pl.BlockSpec((1, bk_dq, D), _kvmap_dq),
            pl.BlockSpec((1, bk_dq, D), _kvmap_dq),
            pl.BlockSpec((1, bq_dq, D), lambda b, j, s: (b, j, 0)),
            pl.BlockSpec((1, bq_dq, _LSE_LANES),
                         lambda b, j, s: (b, j, 0)),
            pl.BlockSpec((1, bq_dq, _LSE_LANES),
                         lambda b, j, s: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq_dq, D), lambda b, j, s: (b, j, 0)),
        scratch_shapes=[pltpu.VMEM((bq_dq, D), jnp.float32)],
        interpret=interpret,
    )(lens, seed_arr, off_arr, qr, kr, vr, do, lse, delta)

    # q/do/lse/delta stream over the innermost grid axis (VMEM bounded by
    # the block size, not Tq — what makes seq >= 4096 compile). Causal
    # runs skip the sub-frontier steps in-kernel; when the offsets are
    # static zeros (every non-ring call) the fetch index also clamps to
    # the frontier so skipped steps re-fetch the block the first live
    # step needs (consecutive equal indices elide the copy). Ring-step
    # (traced) offsets keep the identity map — fetches for skipped steps
    # are wasted bandwidth but never wrong.
    if causal and offsets is None:
        nq_kv = Tq // bq_kv

        def _qmap(b, s, j):
            # lower-clamp to the causal frontier, upper-clamp to the last
            # real Q block (Tk > Tq puts whole k blocks past every q —
            # the body is skipped there, but the fetch must stay in range)
            return (b, jnp.minimum(jnp.maximum(j, (s * bk_kv) // bq_kv),
                                   nq_kv - 1), 0)
    else:
        def _qmap(b, s, j):
            return (b, j, 0)
    scratch = [pltpu.VMEM((bk_kv, D), jnp.float32),
               pltpu.VMEM((bk_kv, D), jnp.float32)]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=bq_kv, block_k=bk_kv,
                          causal=causal, scale=scale, rate=rate,
                          masked=masked),
        out_shape=[
            jax.ShapeDtypeStruct(kr.shape, k.dtype),
            jax.ShapeDtypeStruct(vr.shape, v.dtype),
        ],
        grid=(B * H, Tk // bk_kv, Tq // bq_kv),
        in_specs=[
            _smem_spec(),
            _smem_spec(),
            _smem_spec(),
            pl.BlockSpec((1, bq_kv, D), _qmap),
            pl.BlockSpec((1, bk_kv, D), lambda b, s, j: (b, s, 0)),
            pl.BlockSpec((1, bk_kv, D), lambda b, s, j: (b, s, 0)),
            pl.BlockSpec((1, bq_kv, D), _qmap),
            pl.BlockSpec((1, bq_kv, _LSE_LANES), _qmap),
            pl.BlockSpec((1, bq_kv, _LSE_LANES), _qmap),
        ],
        out_specs=[
            pl.BlockSpec((1, bk_kv, D), lambda b, s, j: (b, s, 0)),
            pl.BlockSpec((1, bk_kv, D), lambda b, s, j: (b, s, 0)),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(lens, seed_arr, off_arr, qr, kr, vr, do, lse, delta)

    return (dq.reshape(B, H, Tq, D), dk.reshape(B, H, Tk, D),
            dv.reshape(B, H, Tk, D))


def _xla_scores(q, k, causal, scale, seq_lens):
    """Masked, scaled [B, H, Tq, Tk] scores of the unfused composition."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    Tq, Tk = q.shape[2], k.shape[2]
    if causal:
        mask = jnp.tril(jnp.ones((Tq, Tk), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    if seq_lens is not None:
        k_pos = jnp.arange(Tk)[None, None, None, :]
        valid = k_pos < jnp.maximum(seq_lens.astype(jnp.int32), 1).reshape(
            -1, 1, 1, 1)
        s = jnp.where(valid, s, _NEG)
    return s


def _xla_attention_lse(q, k, v, causal, scale, seq_lens=None, rate=0.0,
                       rng_key=None):
    """(out, lse) in plain XLA — the differentiable fallback matching
    ``flash_attention_lse``'s two outputs (the PADDLE_TPU_FLASH_BWD
    escape hatch and the op lowering's non-TPU branch, which must bind
    the program's Lse output). With dropout it draws its own jax.random
    mask — statistically, not bitwise, equivalent to the kernel's hash
    RNG; the lse is of the pre-dropout softmax, as in the kernel."""
    s = _xla_scores(q, k, causal, scale, seq_lens)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    w = jnp.exp(s - lse[..., None])
    if rate > 0.0:
        from paddle_tpu.ops.common import hash_keep_mask

        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)
        keep = hash_keep_mask(rng_key, w.shape, rate)
        w = jnp.where(keep, w / (1.0 - rate), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32))
    return out.astype(q.dtype), lse


def _xla_attention(q, k, v, causal, scale, seq_lens=None, rate=0.0,
                   rng_key=None):
    """Unfused reference composition (and the off-TPU fallback)."""
    return _xla_attention_lse(q, k, v, causal, scale, seq_lens, rate,
                              rng_key)[0]


def _check_tileable(q, k, block_q, block_k):
    Tq, Tk = q.shape[2], k.shape[2]
    bq, bk = min(block_q, Tq), min(block_k, Tk)
    if Tq % bq or Tk % bk:
        raise ValueError(
            "flash_attention needs Tq/Tk divisible by the (clamped) block "
            "sizes, got Tq=%d Tk=%d blocks=(%d, %d); use fused_attention "
            "for automatic XLA fallback on odd shapes" % (Tq, Tk, bq, bk))


_BLOCK_TABLE_CACHE = None


def _block_table():
    """Sweep table, cached only on a SUCCESSFUL load — a transient read
    failure (e.g. the file mid-rewrite by the sweep's incremental dump)
    must not pin the heuristic fallback for the process lifetime."""
    global _BLOCK_TABLE_CACHE
    if _BLOCK_TABLE_CACHE is None:
        import json
        import os

        path = os.path.join(os.path.dirname(__file__),
                            "flash_block_table.json")
        try:
            with open(path) as f:
                _BLOCK_TABLE_CACHE = json.load(f)
        except (OSError, ValueError):  # pragma: no cover
            return {}
    return _BLOCK_TABLE_CACHE


def _table_row(t, dtype):
    """Nearest swept row for (dtype, seq); an int (one block for every
    kernel) or a dict {"fwd": int, "dq": [bq, bk], "dkv": [bq, bk]} when
    the backward kernels were swept independently (their residency
    patterns differ: dQ keeps the Q tile resident, dK/dV the K/V tile)."""
    table = _block_table().get(
        jnp.dtype(dtype).name if dtype is not None else "bfloat16")
    if not table:
        return None
    return table[min(table, key=lambda s: abs(int(s) - t))]


def pick_block(t, dtype=None):
    """Forward-kernel block choice, driven by the committed sweep table
    (flash_block_table.json, produced on real hardware by
    tools/flash_block_sweep.py with an interleaved median-of-reps
    protocol — the jit kernel-benchmark discipline of the reference's
    operators/jit/README.en.md). Lookup is by (dtype, nearest swept seq);
    the winning block is clamped to one that tiles ``t``. Heuristic
    fallback (256 when it tiles) if the table is absent. Shared by the
    fused_attention dispatch and bench.py so the benchmark measures the
    production configuration."""
    row = _table_row(t, dtype)
    if row is not None:
        if isinstance(row, dict):
            row = row.get("fwd", 256)
        for blk in (int(row), 256, 128):
            if t % blk == 0 and t >= blk:
                return blk
    return 256 if t % 256 == 0 and t >= 256 else 128


def pick_bwd_blocks(tq, tk, dtype, default):
    """Independent (block_q, block_k) choices for the dQ and dK/dV
    kernels (VERDICT r4 Next #4: the two have different residency
    patterns, so the table MAY tune them apart from the forward). The
    round-5 hardware sweep measured seq-2048 bf16 candidates
    (256/512 combos per kernel) and found no winner outside session
    noise — one-sided runs suggested bq 256/bk 512 at ~5% but an A-B
    validation read identical medians — so the committed table keeps
    shared blocks and this lookup is dormant capability for shapes where
    a future sweep DOES separate them. Returns (dq_blocks, dkv_blocks);
    any entry that does not tile the actual shapes falls back to
    ``default`` (the caller's blocks), so explicit-block callers and
    off-table shapes are never overridden incorrectly."""
    row = _table_row(tk, dtype)
    out = []
    for key in ("dq", "dkv"):
        pair = row.get(key) if isinstance(row, dict) else None
        if (isinstance(pair, (list, tuple)) and len(pair) == 2
                and tq % int(pair[0]) == 0 and tk % int(pair[1]) == 0):
            out.append((int(pair[0]), int(pair[1])))
        else:
            out.append(default)
    return tuple(out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11))
def flash_attention_lse(q, k, v, seq_lens=None, offsets=None, seed=0,
                        causal=False, scale=None, rate=0.0, block_q=128,
                        block_k=128, interpret=False):
    """[B, H, T, D] attention via the Pallas kernels, returning
    ``(out, lse)`` where ``lse`` is the per-row logsumexp of the scaled
    (and masked) scores, [B, H, Tq] float32.

    This is the ring-attention building block: ``offsets`` ([2] int32,
    traced — [q_off, k_off]) places the Q and K blocks at global sequence
    positions so causal masking works across ring steps, and the exposed
    lse lets the caller merge per-step partial outputs with the standard
    logaddexp rescaling. Offsets need not be block-aligned: any row whose
    every key lands ahead of the causal frontier publishes out = 0 with
    lse ~= -1e30 (the kernels guard the fully-masked-row case), which the
    merge maps to weight 0. The lse cotangent is folded into the backward
    kernels' delta (see ``_flash_backward``), so differentiating through
    the merge costs no extra kernel.

    ``seq_lens`` ([B] int) masks keys at positions >= len (padding mask);
    lengths are clamped to >= 1, so a fully-empty sequence attends to key
    position 0 rather than producing NaNs — callers with genuinely empty
    rows must mask the corresponding outputs/loss themselves. ``rate`` is
    in-kernel attention-weight dropout reproduced exactly in the backward
    kernels from ``seed``. Tq/Tk must divide by the (clamped) block sizes
    (ValueError otherwise — ``fused_attention`` handles the fallback).
    """
    out, lse = _fa_fwd(q, k, v, seq_lens, offsets, seed, causal, scale,
                       rate, block_q, block_k, interpret)[0]
    return out, lse


def flash_attention(q, k, v, seq_lens=None, seed=0, causal=False, scale=None,
                    rate=0.0, block_q=128, block_k=128, interpret=False):
    """[B, H, T, D] attention via the Pallas kernels (output only — see
    ``flash_attention_lse`` for semantics; this keeps the historical
    signature used by the op lowerings and the benchmarks)."""
    out, _ = flash_attention_lse(q, k, v, seq_lens, None, seed, causal,
                                 scale, rate, block_q, block_k, interpret)
    return out


def _use_xla_bwd():
    from paddle_tpu import flags as _flags

    return _flags.get_flag("flash_bwd") == "xla"


def _fa_fwd(q, k, v, seq_lens, offsets, seed, causal, scale, rate, block_q,
            block_k, interpret):
    _check_tileable(q, k, block_q, block_k)
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _flash_forward(q, k, v, seq_lens, offsets, seed, causal,
                              scale, rate, block_q, block_k, interpret)
    B, H, Tq = q.shape[0], q.shape[1], q.shape[2]
    lse_pub = lse[..., 0].reshape(B, H, Tq)
    return (out, lse_pub), (q, k, v, out, lse, seq_lens, offsets, seed)


def _fa_bwd_core(q, k, v, out, lse_k, g_out, g_lse, seq_lens, offsets,
                 seed, causal, scale, rate, block_q, block_k, interpret):
    """Shared backward preamble for both custom_vjps: the
    PADDLE_TPU_FLASH_BWD=xla escape hatch (with its dropout/offset
    guards), the table-driven per-kernel block choice, and the
    _flash_backward dispatch. ``lse_k`` is the kernel-layout
    [B*H, Tq, _LSE_LANES] residual; ``g_lse`` the public [B, H, Tq]
    cotangent (or None)."""
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    Tq, Tk = q.shape[2], k.shape[2]
    bq, bk = min(block_q, Tq), min(block_k, Tk)
    if _use_xla_bwd():
        if rate > 0.0:
            raise RuntimeError(
                "PADDLE_TPU_FLASH_BWD=xla cannot be combined with in-kernel "
                "attention dropout: XLA cannot reproduce the kernel's hash "
                "mask. Unset the flag or set dropout_rate=0.")
        if offsets is not None:
            raise RuntimeError(
                "PADDLE_TPU_FLASH_BWD=xla cannot differentiate the "
                "offset (ring-step) form; unset the flag.")
        # escape hatch: recompute attention in XLA (O(T^2) intermediates)
        # for chips where the backward kernels fail to lower. Differentiate
        # the (out, lse) pair so a caller's lse cotangent is not dropped.
        B, H, _ = g_lse.shape if g_lse is not None else (q.shape[0],
                                                        q.shape[1], Tq)
        gl = (g_lse if g_lse is not None
              else jnp.zeros((B, H, Tq), jnp.float32))
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _xla_attention_lse(q_, k_, v_, causal,
                                                  scale_, seq_lens),
            q, k, v)
        return vjp((g_out, gl))
    # table-driven per-kernel blocks apply ONLY when the caller used the
    # table's own forward defaults — an explicit block choice (e.g. to
    # bound VMEM) is never overridden
    if (bq, bk) == (min(pick_block(Tq, q.dtype), Tq),
                    min(pick_block(Tk, q.dtype), Tk)):
        dq_blocks, dkv_blocks = pick_bwd_blocks(Tq, Tk, q.dtype, (bq, bk))
    else:
        dq_blocks = dkv_blocks = (bq, bk)
    return _flash_backward(q, k, v, out, lse_k, g_out, g_lse, seq_lens,
                           offsets, seed, causal, scale_, rate, bq, bk,
                           interpret, dq_blocks=dq_blocks,
                           dkv_blocks=dkv_blocks)


def _fa_bwd(causal, scale, rate, block_q, block_k, interpret, res, g):
    q, k, v, out, lse, seq_lens, offsets, seed = res
    g_out, g_lse = g
    dq, dk, dv = _fa_bwd_core(q, k, v, out, lse, g_out, g_lse, seq_lens,
                              offsets, seed, causal, scale, rate, block_q,
                              block_k, interpret)
    return dq, dk, dv, None, None, None


flash_attention_lse.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def flash_attention_raw_lse(q, k, v, seq_lens, seed, causal, scale, rate,
                            block_q, block_k, interpret):
    """``flash_attention_lse`` with the logsumexp kept in the kernel's
    native [B, H, Tq, _LSE_LANES] tiling (the form the fused_attention op
    saves so the backward read is relayout-free). Carrying its own
    custom_vjp makes the op LOWERING differentiable by jax autodiff —
    the remat lowering (engine/lowering.py lower_block_remat) gradients
    the composed forward instead of running the registered grad op, so
    the pallas_call must not be left to jax's default jvp."""
    out, lse = _flash_forward(q, k, v, seq_lens, None, seed, causal,
                              scale, rate, block_q, block_k, interpret)
    B, H, Tq = q.shape[0], q.shape[1], q.shape[2]
    return out, lse.reshape(B, H, Tq, -1)


def _fa_raw_fwd(q, k, v, seq_lens, seed, causal, scale, rate, block_q,
                block_k, interpret):
    out, lse = _flash_forward(q, k, v, seq_lens, None, seed, causal,
                              scale, rate, block_q, block_k, interpret)
    B, H, Tq = q.shape[0], q.shape[1], q.shape[2]
    lse_raw = lse.reshape(B, H, Tq, -1)
    return (out, lse_raw), (q, k, v, out, lse_raw, seq_lens, seed)


def _fa_raw_bwd(causal, scale, rate, block_q, block_k, interpret,
                res, g):
    q, k, v, out, lse_raw, seq_lens, seed = res
    g_out, g_lse_raw = g
    B, H, Tq, _ = q.shape
    # raw lse replicates the row value across lanes, so the public
    # cotangent is the lane sum (zeros when nothing consumed the lse)
    g_lse = None if g_lse_raw is None else g_lse_raw.sum(axis=-1)
    lse_k = lse_raw.reshape(B * H, Tq, -1)
    dq, dk, dv = _fa_bwd_core(q, k, v, out, lse_k, g_out, g_lse, seq_lens,
                              None, seed, causal, scale, rate, block_q,
                              block_k, interpret)
    return dq, dk, dv, None, None


flash_attention_raw_lse.defvjp(_fa_raw_fwd, _fa_raw_bwd)


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _flash_min_seq():
    try:
        from paddle_tpu import flags as _flags

        return int(_flags.get_flag("flash_min_seq"))
    except ValueError:  # pragma: no cover
        return 256


def flash_dispatch_ok(tq, tk):
    """Whether the Pallas kernels apply to a (Tq, Tk) attention: pallas-TPU
    importable, real TPU backend, tileable blocks, and at least
    PADDLE_TPU_FLASH_MIN_SEQ keys (the measured crossover — see
    ``fused_attention``). The single dispatch predicate shared by
    ``fused_attention`` and the ring-attention body so the two paths can
    never diverge."""
    tileable = tq % min(128, tq) == 0 and tk % min(128, tk) == 0
    return (_HAS_PLTPU and _on_tpu() and tileable
            and tk >= _flash_min_seq())


# --- SPMD (shard_map) wrapping ---------------------------------------------
# When a block is being traced for a mesh (engine/executor.py sets the
# parallel.mesh.spmd_lowering context), the attention dispatch and the
# direct flash backward wrap themselves in shard_map over the mesh's
# data-parallel and tensor axes — attention is independent per
# (batch, head), so splitting those dims is exact, each shard runs the
# Pallas kernels at local shape, and XLA never tries to partition a
# pallas_call it cannot see into. Same construction as
# parallel/ring_attention.py's sp-axis ring (which remains the sequence
# axis story; these wraps leave the sequence dim whole).

try:
    from jax import shard_map as _shard_map_raw
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map_raw


def _shard_map(body, mesh, in_specs, out_specs):
    """shard_map with replication checking off, across the jax rename
    (check_vma today, check_rep before)."""
    try:
        return _shard_map_raw(body, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs)
    except TypeError:
        return _shard_map_raw(body, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)


def _spmd_attention_axes(B, H):
    """(mesh, batch_axes, head_axis) for the active SPMD lowering
    context, or None when no wrap applies: no context, 1-way axes, or
    indivisible batch/head dims (each falls back to the unwrapped
    single-device trace — a 1-device mesh is bit-identical by
    construction)."""
    from paddle_tpu.parallel.mesh import current_spmd

    spmd = current_spmd()
    if spmd is None:
        return None
    mesh, data_axes = spmd
    batch_axes = tuple(a for a in data_axes if a in mesh.axis_names)
    bsz = 1
    for a in batch_axes:
        bsz *= mesh.shape[a]
    if not (bsz > 1 and B % bsz == 0):
        batch_axes = ()
    head_axis = None
    if ("tp" in mesh.axis_names and "tp" not in batch_axes
            and mesh.shape["tp"] > 1 and H % mesh.shape["tp"] == 0):
        head_axis = "tp"
    if not batch_axes and head_axis is None:
        return None
    return mesh, batch_axes, head_axis


def _batch_spec_entry(batch_axes):
    if not batch_axes:
        return None
    return batch_axes if len(batch_axes) > 1 else batch_axes[0]


def _shard_seed(seed, mesh, batch_axes, head_axis):
    """Per-shard dropout seed: fold the linear shard index in so shards
    draw decorrelated masks (the kernel's hash RNG indexes by LOCAL
    (b, q, k) coordinates, which repeat across shards). Deterministic in
    (seed, shard), and identical in the forward and backward wraps, so
    the backward kernels still regenerate the forward's exact mask."""
    idx = jnp.int32(0)
    for a in tuple(batch_axes) + ((head_axis,) if head_axis else ()):
        idx = idx * jnp.int32(mesh.shape[a]) + jax.lax.axis_index(
            a).astype(jnp.int32)
    return jnp.asarray(seed, jnp.int32) + idx * jnp.int32(1000003)


def _dispatch_local(q, k, v, causal, scale, seq_lens, dropout_rate, seed,
                    force_pallas, raw_lse):
    """Single-device (or per-shard) dispatch core of
    ``dispatch_attention_lse``."""
    Tq, Tk = q.shape[2], k.shape[2]
    B, H = q.shape[0], q.shape[1]
    bq, bk = pick_block(Tq, q.dtype), pick_block(Tk, q.dtype)
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    use_pallas = (force_pallas if force_pallas is not None
                  else flash_dispatch_ok(Tq, Tk))
    if use_pallas:
        if raw_lse:
            _check_tileable(q, k, bq, bk)
            return flash_attention_raw_lse(
                q, k, v, seq_lens, seed, causal, scale_,
                dropout_rate, bq, bk, not _on_tpu())
        return flash_attention_lse(q, k, v, seq_lens, None, seed, causal,
                                   scale_, dropout_rate, bq, bk,
                                   not _on_tpu())
    key = jax.random.PRNGKey(seed) if dropout_rate > 0.0 else None
    out, lse = _xla_attention_lse(q, k, v, causal, scale_, seq_lens,
                                  dropout_rate, key)
    if raw_lse:
        lse = jnp.broadcast_to(lse[..., None], (B, H, Tq, _LSE_LANES))
    return out, lse


def dispatch_attention_lse(q, k, v, causal=False, scale=None, seq_lens=None,
                           dropout_rate=0.0, seed=0, force_pallas=None,
                           raw_lse=False):
    """THE shared (out, lse) attention dispatch: the Pallas kernels when
    ``flash_dispatch_ok`` (block table + interpret flag resolved here, in
    exactly one place), the XLA composition otherwise. ``fused_attention``,
    the fused_attention op lowering, and the registered grad op's
    recompute fallback all route through this function, so the forward a
    gradient differentiates can never silently diverge from the forward
    that produced the saved Out.

    Under an active SPMD lowering context (the engine tracing a block
    for a mesh) the whole dispatch additionally wraps itself in
    ``shard_map`` over the mesh's data axes (batch dim) and ``tp`` axis
    (head dim) — exact per-(batch, head) decomposition, so sharded
    models get the flash kernels per shard instead of an XLA-partitioned
    approximation of the custom call.

    ``raw_lse=True`` returns the logsumexp in the kernel's native tiling
    carried as ``[B, H, Tq, _LSE_LANES]`` float32 (a major-dim-only
    reshape of the kernel's [B*H, Tq, LANES] — layout-preserving, and
    the leading dim keeps the build-time batch sentinel intact) instead
    of the public ``[B, H, Tq]``. The fused_attention op saves it this
    way so the backward kernels read it with zero relayout (the
    [B,H,T] <-> [B*H,T,1] round trip doesn't commute with TPU tiling;
    the round-5 seq-2048 trace showed 12 x ~0.08 ms/step of lse layout
    copies). Only meaningful on the forward-only (op) path — the
    custom_vjp keeps the public form."""
    spmd = _spmd_attention_axes(q.shape[0], q.shape[1])
    if spmd is None:
        return _dispatch_local(q, k, v, causal, scale, seq_lens,
                               dropout_rate, seed, force_pallas, raw_lse)
    mesh, batch_axes, head_axis = spmd
    from jax.sharding import PartitionSpec as P

    bspec = _batch_spec_entry(batch_axes)
    qspec = P(bspec, head_axis, None, None)
    out_specs = (qspec,
                 P(bspec, head_axis, None, None) if raw_lse
                 else P(bspec, head_axis, None))
    seed_in = jnp.asarray(seed, jnp.int32)

    def body(q_, k_, v_, seed_, lens_):
        if dropout_rate > 0.0:
            seed_ = _shard_seed(seed_, mesh, batch_axes, head_axis)
        return _dispatch_local(q_, k_, v_, causal, scale, lens_,
                               dropout_rate, seed_, force_pallas, raw_lse)

    if seq_lens is not None:
        fn = _shard_map(
            body, mesh=mesh,
            in_specs=(qspec, qspec, qspec, P(), P(bspec)),
            out_specs=out_specs)
        return fn(q, k, v, seed_in, seq_lens)
    fn = _shard_map(
        lambda q_, k_, v_, s_: body(q_, k_, v_, s_, None), mesh=mesh,
        in_specs=(qspec, qspec, qspec, P()),
        out_specs=out_specs)
    return fn(q, k, v, seed_in)


def flash_backward_spmd(q, k, v, out, lse_k, g, seq_lens, seed, causal,
                        scale, rate, block_q, block_k, interpret,
                        dq_blocks=None, dkv_blocks=None):
    """``_flash_backward`` for the registered grad op, shard_mapped over
    the active mesh's data/tp axes when an SPMD lowering context is up
    (per-(batch, head) independence makes the wrap exact — the same
    decomposition the forward dispatch used, so the saved Out/Lse shards
    line up); plain direct call otherwise. ``lse_k`` arrives in the
    kernel's [B*H, Tq, LANES] layout; the wrap splits its leading dim as
    [B, H, Tq, LANES] (metadata-only) to shard batch and heads, and
    re-flattens per shard."""
    B, H, Tq, _D = q.shape
    spmd = _spmd_attention_axes(B, H)
    if spmd is None:
        return _flash_backward(q, k, v, out, lse_k, g, None, seq_lens,
                               None, seed, causal, scale, rate, block_q,
                               block_k, interpret, dq_blocks=dq_blocks,
                               dkv_blocks=dkv_blocks)
    mesh, batch_axes, head_axis = spmd
    from jax.sharding import PartitionSpec as P

    bspec = _batch_spec_entry(batch_axes)
    qspec = P(bspec, head_axis, None, None)
    lse4 = lse_k.reshape(B, H, Tq, -1)
    seed_in = jnp.asarray(seed, jnp.int32)

    def body(q_, k_, v_, out_, lse4_, g_, seed_, lens_):
        if rate > 0.0:
            seed_ = _shard_seed(seed_, mesh, batch_axes, head_axis)
        Bl, Hl = q_.shape[0], q_.shape[1]
        return _flash_backward(
            q_, k_, v_, out_, lse4_.reshape(Bl * Hl, Tq, -1), g_, None,
            lens_, None, seed_, causal, scale, rate, block_q, block_k,
            interpret, dq_blocks=dq_blocks, dkv_blocks=dkv_blocks)

    out_specs = (qspec, qspec, qspec)
    if seq_lens is not None:
        fn = _shard_map(
            body, mesh=mesh,
            in_specs=(qspec, qspec, qspec, qspec, qspec, qspec, P(),
                      P(bspec)),
            out_specs=out_specs)
        return fn(q, k, v, out, lse4, g, seed_in, seq_lens)
    fn = _shard_map(
        lambda q_, k_, v_, o_, l_, g_, s_: body(q_, k_, v_, o_, l_, g_,
                                                s_, None),
        mesh=mesh,
        in_specs=(qspec, qspec, qspec, qspec, qspec, qspec, P()),
        out_specs=out_specs)
    return fn(q, k, v, out, lse4, g, seed_in)


def fused_attention(q, k, v, causal=False, scale=None, seq_lens=None,
                    dropout_rate=0.0, seed=0, force_pallas=None):
    """Dispatch point for whole-attention fusion: the Pallas flash kernels
    on TPU for sequences of at least PADDLE_TPU_FLASH_MIN_SEQ (default
    256) keys, the plain-XLA composition elsewhere (short sequences, odd
    shapes, non-TPU backends).

    The threshold is measured, not aesthetic: at short T the [T, T] score
    matrix is tiny, XLA's batched matmul+softmax fusion wins, and flash's
    per-program overhead costs ~15% end-to-end on BERT seq-128; from
    ~256-512 keys up the O(T^2) materialization starts losing to the
    streaming kernel (1.1-1.3x at seq 2048) and flash's O(T) memory is
    what makes long-context training fit at all. ``seq_lens`` lengths are
    clamped to >= 1 (see flash_attention). ``force_pallas=True`` runs the
    kernel in interpreter mode off-TPU (tests)."""
    return dispatch_attention_lse(q, k, v, causal, scale, seq_lens,
                                  dropout_rate, seed, force_pallas)[0]
