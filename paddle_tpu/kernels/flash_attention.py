"""Flash attention as a Pallas TPU kernel.

Streams K/V blocks from VMEM against a resident Q block with online-softmax
accumulation — O(T) memory, MXU-shaped contractions (the kernel the
reference implements as math/softmax.cu + matmuls, fused here instead).

``fused_attention`` is the dispatch point: the Pallas kernel on TPU (or in
interpreter mode for tests), the plain-XLA composition elsewhere. The
backward pass recomputes attention in XLA (flash-style backward kernel is a
follow-up; recompute keeps training memory at O(T) like jax.checkpoint
would).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, scale,
                 block_q):
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    j = pl.program_id(1)
    T = k_ref.shape[1]
    nk = T // block_k

    q_pos = j * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(s, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(s * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(s * block_k, block_k), :].astype(jnp.float32)
        sij = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = s * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            sij = jnp.where(q_pos >= k_pos, sij, _NEG)
        m_new = jnp.maximum(m, jnp.max(sij, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(sij - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, q_ref.shape[2]), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)

    if causal:
        # blocks fully above the diagonal contribute nothing — skip them
        nk_eff = jnp.minimum(
            nk, (j + 1) * block_q // block_k + (1 if block_q % block_k else 0)
        )
        nk_eff = jnp.maximum(nk_eff, 1)
    else:
        nk_eff = nk
    acc, m, l = jax.lax.fori_loop(0, nk_eff, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    B, H, T, D = q.shape
    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    grid = (B * H, T // block_q)

    kernel = functools.partial(
        _attn_kernel, block_k=block_k, causal=causal, scale=scale,
        block_q=block_q)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qr.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, T, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, j: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, j: (b, j, 0)),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(B, H, T, D)


def _xla_attention(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128, interpret=False):
    """[B, H, T, D] attention via the Pallas kernel; T must divide by the
    block sizes (clamped to T)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret)


def _fa_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    out = _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _fa_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _xla_attention(q_, k_, v_, causal, scale_),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def fused_attention(q, k, v, causal=False, scale=None, force_pallas=None):
    """Pallas flash attention on TPU; plain-XLA composition elsewhere.
    ``force_pallas=True`` runs the kernel in interpreter mode off-TPU
    (tests)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    T = q.shape[2]
    use_pallas = force_pallas if force_pallas is not None else (
        _HAS_PLTPU and _on_tpu() and T % 128 == 0)
    if use_pallas:
        return flash_attention(q, k, v, causal, scale,
                               interpret=not _on_tpu())
    return _xla_attention(q, k, v, causal, scale)
