"""Pallas TPU kernels — the framework's answer to the reference's
hand-written CUDA/JIT kernel layer (reference: paddle/fluid/operators/*.cu +
operators/jit/ xbyak codegen): XLA fuses the bulk; these kernels cover the
patterns worth hand-scheduling (flash attention today; quantized matmul and
ragged ops next)."""

from paddle_tpu.kernels.flash_attention import (  # noqa: F401
    flash_attention,
    fused_attention,
)
