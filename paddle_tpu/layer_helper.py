"""LayerHelper (reference: python/paddle/fluid/layer_helper.py) — shared
machinery for layers: parameter creation (with startup-program init ops),
temp-variable creation, op appending, bias/activation tails."""

import copy

from paddle_tpu import unique_name
from paddle_tpu.framework import (
    default_main_program,
    default_startup_program,
    Variable,
)
from paddle_tpu.initializer import ConstantInitializer, XavierInitializer
from paddle_tpu.param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type, block=None, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        self._block = block
        if kwargs.get("name") is None:
            self.kwargs["name"] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs["name"]

    @property
    def main_program(self):
        return self._block.program if self._block is not None else default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self._block if self._block is not None else self.main_program.current_block()

    def append_op(self, *args, **kwargs):
        return self.block.append_op(*args, **kwargs)

    # -- params ------------------------------------------------------------
    def param_attr_or_default(self, attr, default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            attr = ParamAttr()
        if attr.initializer is None:
            attr.initializer = default_initializer or XavierInitializer()
        return attr

    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            attr = ParamAttr()
        else:
            attr = copy.copy(attr)
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w_0" if not is_bias else "b_0"]))
        if attr.initializer is None:
            attr.initializer = (
                ConstantInitializer(0.0)
                if is_bias
                else (default_initializer or XavierInitializer())
            )

        startup_block = self.startup_program.global_block()
        # A shared parameter (same ParamAttr name, e.g. word2vec's
        # "shared_w") is created once per referencing layer; only the
        # first creation appends an init op, or the startup program would
        # initialize the var N times (reference: framework.py
        # Block.create_parameter skips an already-inited param).
        already_inited = any(
            attr.name in op.output_arg_names()
            for op in startup_block.desc.ops
        )
        sv = startup_block.create_var(
            name=attr.name, shape=shape, dtype=dtype, persistable=True
        )
        if not already_inited:
            attr.initializer(sv, startup_block)

        param = self.main_program.global_block().create_parameter(
            name=attr.name,
            shape=shape,
            dtype=dtype,
            trainable=attr.trainable,
            regularizer=attr.regularizer,
            gradient_clip_attr=attr.gradient_clip,
        )
        param.optimize_attr = {"learning_rate": attr.learning_rate}
        return param

    # -- temps -------------------------------------------------------------
    def create_variable_for_type_inference(self, dtype="float32", stop_gradient=False):
        return self.block.create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype,
            shape=None,
            stop_gradient=stop_gradient,
        )

    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.block.create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, stop_gradient=True, **kwargs
        )

    def set_variable_initializer(self, var, initializer):
        startup_block = self.startup_program.global_block()
        sv = startup_block.create_var(
            name=var.name,
            shape=var.shape,
            dtype=var.dtype,
            persistable=True,
        )
        initializer(sv, startup_block)

    # -- tails -------------------------------------------------------------
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.kwargs.get("bias_attr")
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        bias = self.create_parameter(
            bias_attr if bias_attr not in (None, True) else ParamAttr(),
            shape=size,
            dtype=input_var.dtype,
            is_bias=True,
        )
        out = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [bias]},
            outputs={"Out": [out]},
            attrs={"axis": dim_start},
        )
        return out

    def append_activation(self, input_var):
        act = self.kwargs.get("act")
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act = copy.copy(act)
        act_type = act.pop("type")
        out = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type=act_type,
            inputs={"X": [input_var]},
            outputs={"Out": [out]},
            attrs=act,
        )
        return out

    def input_dtype(self, input_param_name="input"):
        v = self.kwargs.get(input_param_name)
        if isinstance(v, (list, tuple)):
            v = v[0]
        return v.dtype
