from paddle_tpu.layers.tensor import *  # noqa: F401,F403
from paddle_tpu.layers.nn import *  # noqa: F401,F403
from paddle_tpu.layers.control_flow import (  # noqa: F401
    While,
    StaticRNN,
    DynamicRNN,
    IfElse,
    Switch,
    create_array,
    array_write,
    array_read,
    array_length,
    increment,
)
from paddle_tpu.layers.ops import *  # noqa: F401,F403
from paddle_tpu.layers.io import (  # noqa: F401
    data,
    py_reader,
    double_buffer,
    PyReader,
    batch,
    shuffle,
    open_files,
    read_file,
    create_py_reader_by_data,
    random_data_generator,
    Preprocessor,
)
from paddle_tpu.layers.loss import *  # noqa: F401,F403
from paddle_tpu.layers import detection  # noqa: F401
from paddle_tpu.layers.detection import *  # noqa: F401,F403
from paddle_tpu.layers.metric_op import accuracy, auc  # noqa: F401
from paddle_tpu.layers import learning_rate_scheduler  # noqa: F401
from paddle_tpu.layers.learning_rate_scheduler import (  # noqa: F401
    append_LARS,
    exponential_decay,
    natural_exp_decay,
    inverse_time_decay,
    polynomial_decay,
    piecewise_decay,
    noam_decay,
    cosine_decay,
    linear_lr_warmup,
)
