"""Auto-generated unary activation layers (reference:
python/paddle/fluid/layers/ops.py via layer_function_generator.py)."""

from paddle_tpu.layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "softshrink",
    "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin", "round",
    "reciprocal", "square", "softplus", "softsign", "hard_sigmoid",
    "swish", "relu6", "elu", "gelu", "brelu", "soft_relu", "hard_shrink",
    "thresholded_relu", "stanh", "sign", "log",
]

__all__ = list(_UNARY_OPS) + ["uniform_random"]


def _make_unary(op_type):
    def layer(x, name=None, **kwargs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(
            type=op_type,
            inputs={"X": [x]},
            outputs={"Out": [out]},
            attrs=kwargs,
        )
        return out

    layer.__name__ = op_type
    return layer


for _op in _UNARY_OPS:
    globals()[_op] = _make_unary(_op)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    from paddle_tpu.core.types import convert_np_dtype_to_dtype_

    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="uniform_random",
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": int(convert_np_dtype_to_dtype_(dtype)),
            "min": float(min),
            "max": float(max),
            "seed": seed,
        },
    )
    return out
