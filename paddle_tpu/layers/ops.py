"""Auto-generated unary activation layers (reference:
python/paddle/fluid/layers/ops.py via layer_function_generator.py)."""

from paddle_tpu.layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "softshrink",
    "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin", "round",
    "reciprocal", "square", "softplus", "softsign", "hard_sigmoid",
    "swish", "relu6", "elu", "gelu", "brelu", "soft_relu", "hard_shrink",
    "thresholded_relu", "stanh", "sign", "log",
]

__all__ = list(_UNARY_OPS) + ["uniform_random"]


# Attr names + reference defaults for the parameterized activations
# (reference: the op makers in paddle/fluid/operators/activation_op.cc).
# Declaring them gives each layer an explicit signature — the API golden
# test (tests/test_api_spec.py) no longer accepts a **kwargs stub here.
_UNARY_ATTRS = {
    "elu": (("alpha", 1.0),),
    "relu6": (("threshold", 6.0),),
    "stanh": (("scale_a", 2.0 / 3.0), ("scale_b", 1.7159)),
    "hard_sigmoid": (("slope", 0.2), ("offset", 0.5)),
    "swish": (("beta", 1.0),),
    "brelu": (("t_min", 0.0), ("t_max", 24.0)),
    "soft_relu": (("threshold", 40.0),),
    "hard_shrink": (("threshold", 0.5),),
    "thresholded_relu": (("threshold", 1.0),),
}


def _make_unary(op_type):
    import inspect

    attr_spec = _UNARY_ATTRS.get(op_type)

    if attr_spec is None:
        def layer(x, name=None, **kwargs):
            helper = LayerHelper(op_type, name=name)
            out = helper.create_variable_for_type_inference(dtype=x.dtype)
            helper.append_op(
                type=op_type,
                inputs={"X": [x]},
                outputs={"Out": [out]},
                attrs=kwargs,
            )
            return out

        layer.__name__ = op_type
        return layer

    P = inspect.Parameter
    sig = inspect.Signature(
        [P("x", P.POSITIONAL_OR_KEYWORD)]
        + [P(k, P.POSITIONAL_OR_KEYWORD, default=v)
           for k, v in attr_spec]
        + [P("name", P.POSITIONAL_OR_KEYWORD, default=None)])

    def layer(*args, **kwargs):
        bound = sig.bind(*args, **kwargs)
        bound.apply_defaults()
        x = bound.arguments.pop("x")
        name = bound.arguments.pop("name")
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(
            type=op_type,
            inputs={"X": [x]},
            outputs={"Out": [out]},
            attrs=dict(bound.arguments),
        )
        return out

    layer.__name__ = op_type
    layer.__signature__ = sig
    return layer


for _op in _UNARY_OPS:
    globals()[_op] = _make_unary(_op)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    from paddle_tpu.core.types import convert_np_dtype_to_dtype_

    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="uniform_random",
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": int(convert_np_dtype_to_dtype_(dtype)),
            "min": float(min),
            "max": float(max),
            "seed": seed,
        },
    )
    return out
