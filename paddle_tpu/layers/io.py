"""Data-entry layers (reference: python/paddle/fluid/layers/io.py — data:39,
py_reader:636, double_buffer:1005)."""

import pickle
import threading

import numpy as np

from paddle_tpu.framework import default_main_program
from paddle_tpu.core.types import VarType, convert_dtype_to_np
from paddle_tpu.native import BlockingQueue


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True, type=VarType.LOD_TENSOR):
    """Declare a feed variable (reference: layers/io.py:39). With
    ``append_batch_size`` a -1 batch dim is prepended, exactly like the
    reference."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().current_block()
    if name in block.vars:
        return block.vars[name]
    return block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        stop_gradient=stop_gradient,
        type=type,
    )


class PyReader:
    """Decoupled feeding: a background thread decodes batches through the
    native blocking queue; ``Executor.run`` with no explicit feed pops the
    next batch for this program (reference: layers/io.py:636 py_reader over
    LoDTensorBlockingQueue + double_buffer — prefetch overlaps device
    execution)."""

    def __init__(self, feed_vars, capacity):
        self.vars = list(feed_vars)
        self.var_names = [v.name for v in self.vars]
        self._dtypes = [convert_dtype_to_np(v.dtype) for v in self.vars]
        self._queue = BlockingQueue(capacity=capacity)
        self._thread = None
        self._reader = None
        self._exhausted = False

    def decorate_paddle_reader(self, reader):
        """reader() yields per-batch tuples aligned with the declared
        vars."""
        self._reader = reader

    decorate_batch_generator = decorate_paddle_reader
    decorate_sample_list_generator = decorate_paddle_reader

    def start(self):
        assert self._reader is not None, "decorate a reader before start()"
        self._queue.reset()
        self._exhausted = False

        def producer():
            try:
                for batch in self._reader():
                    arrays = [
                        np.asarray(x, dtype=dt)
                        for x, dt in zip(batch, self._dtypes)
                    ]
                    payload = pickle.dumps(arrays, protocol=4)
                    if not self._queue.push(payload):
                        return
            finally:
                self._queue.close()

        self._thread = threading.Thread(target=producer, daemon=True)
        self._thread.start()

    def next_feed(self):
        """dict name->array, or None when the epoch is exhausted."""
        item = self._queue.pop()
        if item is None:
            self._exhausted = True
            return None
        arrays = pickle.loads(item)
        return dict(zip(self.var_names, arrays))

    def reset(self):
        self._queue.close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._queue.reset()


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Create feed vars + a PyReader pump registered on the program
    (reference API: layers/io.py:636). Returns the PyReader; its ``.vars``
    are the program inputs."""
    from paddle_tpu import unique_name

    program = default_main_program()
    feed_vars = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        vname = unique_name.generate("%s_slot_%d" % (name or "py_reader", i))
        feed_vars.append(data(
            name=vname, shape=list(shape), dtype=dtype,
            append_batch_size=False))
    reader = PyReader(feed_vars, capacity)
    if not hasattr(program, "_py_readers"):
        program._py_readers = []
    program._py_readers.append(reader)
    return reader


def double_buffer(reader, place=None, name=None):
    """Kept for API parity — prefetch is inherent to PyReader's queue."""
    return reader
