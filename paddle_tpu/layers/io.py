"""Data-entry layers (reference: python/paddle/fluid/layers/io.py — data:39,
py_reader:636, double_buffer:1005)."""

import pickle
import threading

import numpy as np

from paddle_tpu.framework import default_main_program
from paddle_tpu.core.types import VarType, convert_dtype_to_np
from paddle_tpu.native import BlockingQueue


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True, type=VarType.LOD_TENSOR):
    """Declare a feed variable (reference: layers/io.py:39). With
    ``append_batch_size`` a -1 batch dim is prepended, exactly like the
    reference."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().current_block()
    if name in block.vars:
        return block.vars[name]
    return block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        stop_gradient=stop_gradient,
        type=type,
    )


class PyReader:
    """Decoupled feeding: a background thread decodes batches through the
    native blocking queue; ``Executor.run`` with no explicit feed pops the
    next batch for this program (reference: layers/io.py:636 py_reader over
    LoDTensorBlockingQueue + double_buffer — prefetch overlaps device
    execution)."""

    def __init__(self, feed_vars, capacity):
        self.vars = list(feed_vars)
        self.var_names = [v.name for v in self.vars]
        self._dtypes = [convert_dtype_to_np(v.dtype) for v in self.vars]
        self._queue = BlockingQueue(capacity=capacity)
        self._thread = None
        self._reader = None
        self._exhausted = False

    def decorate_paddle_reader(self, reader):
        """reader() yields per-batch tuples aligned with the declared
        vars."""
        self._reader = reader

    decorate_batch_generator = decorate_paddle_reader
    decorate_sample_list_generator = decorate_paddle_reader

    def start(self):
        assert self._reader is not None, "decorate a reader before start()"
        self._queue.reset()
        self._exhausted = False

        def producer():
            try:
                for batch in self._reader():
                    arrays = [
                        np.asarray(x, dtype=dt)
                        for x, dt in zip(batch, self._dtypes)
                    ]
                    payload = pickle.dumps(arrays, protocol=4)
                    if not self._queue.push(payload):
                        return
            finally:
                self._queue.close()

        self._thread = threading.Thread(target=producer, daemon=True)
        self._thread.start()

    def next_feed(self):
        """dict name->array, or None when the epoch is exhausted."""
        item = self._queue.pop()
        if item is None:
            self._exhausted = True
            return None
        arrays = pickle.loads(item)
        return dict(zip(self.var_names, arrays))

    def reset(self):
        self._queue.close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._queue.reset()


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Create feed vars + a PyReader pump registered on the program
    (reference API: layers/io.py:636). Returns the PyReader; its ``.vars``
    are the program inputs."""
    from paddle_tpu import unique_name

    program = default_main_program()
    feed_vars = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        vname = unique_name.generate("%s_slot_%d" % (name or "py_reader", i))
        feed_vars.append(data(
            name=vname, shape=list(shape), dtype=dtype,
            append_batch_size=False))
    reader = PyReader(feed_vars, capacity)
    if not hasattr(program, "_py_readers"):
        program._py_readers = []
    program._py_readers.append(reader)
    return reader


def double_buffer(reader, place=None, name=None):
    """Kept for API parity — prefetch is inherent to PyReader's queue."""
    return reader


def batch(reader, batch_size):
    """(reference: layers/io.py batch — the old C++ reader-op form).
    TPU-native redesign: file readers are python readers (see
    paddle_tpu.reader); this is the same batching decorator under the
    reference's layer name."""
    from paddle_tpu.reader.decorator import batch as _batch

    return _batch(reader, batch_size)


def shuffle(reader, buffer_size):
    """(reference: layers/io.py shuffle) — python-reader decorator form."""
    from paddle_tpu.reader.decorator import shuffle as _shuffle

    return _shuffle(reader, buffer_size)


def open_files(filenames, shapes=None, lod_levels=None, dtypes=None,
               thread_num=None, buffer_size=None, pass_num=1,
               is_test=None):
    """(reference: layers/io.py open_files — RecordIO file reader ops).
    TPU-native redesign: returns a python reader over the RecordIO files;
    pair with fluid.layers.batch / PyReader for feeding. Each record is
    yielded as raw bytes unless shapes/dtypes are given, in which case
    records are parsed as flat arrays of the declared dtype/shape tuple."""
    import numpy as np

    from paddle_tpu import recordio

    if isinstance(filenames, str):
        filenames = [filenames]
    if bool(shapes) != bool(dtypes):
        raise ValueError(
            "open_files: give BOTH shapes and dtypes (to parse records "
            "into arrays) or NEITHER (raw bytes)")

    def reader():
        for _ in range(pass_num):
            for fname in filenames:
                for rec in recordio.Reader(fname):
                    if not dtypes:
                        yield rec
                        continue
                    out, off = [], 0
                    for shape, dtype in zip(shapes, dtypes):
                        n = int(np.prod(shape))
                        arr = np.frombuffer(
                            rec, dtype=dtype, count=n,
                            offset=off).reshape(shape)
                        off += arr.nbytes
                        out.append(arr)
                    yield tuple(out)

    return reader


def read_file(reader):
    """(reference: layers/io.py read_file). With python readers there is
    no in-graph file op; feed via DataFeeder or PyReader instead."""
    raise NotImplementedError(
        "read_file consumed the C++ reader ops; use the returned python "
        "reader with fluid.DataFeeder or fluid.layers.py_reader "
        "(see open_files docstring)")


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """(reference: layers/io.py create_py_reader_by_data) — a PyReader
    built from existing data vars instead of shapes/dtypes."""
    from paddle_tpu.core.types import convert_dtype_to_np

    shapes = [list(v.shape) for v in feed_list]
    dtypes = [str(convert_dtype_to_np(v.dtype)) for v in feed_list]
    return py_reader(capacity=capacity, shapes=shapes, dtypes=dtypes,
                     name=name, use_double_buffer=use_double_buffer)


def random_data_generator(low, high, shapes, lod_levels=None,
                          for_parallel=True):
    """(reference: layers/io.py random_data_generator) — python reader of
    uniform random tuples."""
    import numpy as np

    def reader():
        rng = np.random.RandomState()  # fresh stream per reader instance
        while True:
            yield tuple(
                rng.uniform(low, high, s).astype(np.float32)
                for s in shapes)

    return reader


class Preprocessor:
    """In-graph reader preprocessing block (reference: layers/io.py:1082
    create_custom_reader/Preprocessor). The reference runs the sub-block
    per batch inside the C++ custom-reader op; here the block is built in
    its own Program and jit-compiled once through the engine, so the
    per-batch transform runs as a single XLA executable — the TPU-native
    equivalent of the reference's sub-block execution.

    Usage matches the reference::

        p = fluid.layers.Preprocessor(reader=my_py_reader)
        with p.block():
            img, lbl = p.inputs()
            p.outputs(img / 2, lbl + 1)
        new_reader = p()              # python reader of transformed tuples

    ``reader`` is a python batch reader (callable yielding tuples);
    ``shapes``/``dtypes`` describe its slots (needed to declare the
    sub-block inputs; they may carry -1 batch dims).
    """

    BEFORE_SUB_BLOCK = 0
    IN_SUB_BLOCK = 1
    AFTER_SUB_BLOCK = 2

    def __init__(self, reader, name=None, shapes=None, dtypes=None):
        from paddle_tpu import unique_name

        self.underlying_reader = reader
        self.name = name or unique_name.generate("create_custom_reader")
        self.shapes = shapes
        self.dtypes = dtypes
        if shapes is None and hasattr(reader, "vars"):
            # a PyReader carries its slot declarations
            self.shapes = [list(v.shape) for v in reader.vars]
            self.dtypes = [str(convert_dtype_to_np(v.dtype))
                           for v in reader.vars]
        self.sub_program = None
        self.source_vars = None
        self.sink_var_names = None
        self.status = Preprocessor.BEFORE_SUB_BLOCK

    def _is_completed(self):
        return (self.sub_program is not None and self.source_vars
                and self.sink_var_names)

    def block(self):
        import contextlib

        from paddle_tpu.framework import Program, program_guard

        @contextlib.contextmanager
        def guard():
            self.status = Preprocessor.IN_SUB_BLOCK
            self.sub_program = Program()
            self._startup = Program()
            with program_guard(self.sub_program, self._startup):
                yield
            self.status = Preprocessor.AFTER_SUB_BLOCK
            if not self._is_completed():
                raise RuntimeError(
                    "The definition of preprocessor is incomplete! Set "
                    "input and output variables via 'inputs' and "
                    "'outputs' inside the sub-block.")

        return guard()

    def inputs(self):
        if self.status != Preprocessor.IN_SUB_BLOCK:
            raise RuntimeError(
                "Preprocessor.inputs() can only be invoked inside the "
                "sub-block.")
        if self.shapes is None or self.dtypes is None:
            raise ValueError(
                "Preprocessor needs BOTH shapes and dtypes (or a "
                "PyReader) to declare its sub-block inputs")
        from paddle_tpu import unique_name

        self.source_vars = [
            data(name=unique_name.generate("preprocessor_source"),
                 shape=list(shape), dtype=dtype, append_batch_size=False)
            for shape, dtype in zip(self.shapes, self.dtypes)
        ]
        return self.source_vars

    def outputs(self, *outs):
        if self.status != Preprocessor.IN_SUB_BLOCK:
            raise RuntimeError(
                "Preprocessor.outputs() can only be invoked inside the "
                "sub-block.")
        self.sink_var_names = [v.name for v in outs]

    def __call__(self, *args, **kwargs):
        if self.status != Preprocessor.AFTER_SUB_BLOCK:
            raise RuntimeError(
                "Preprocessor output can only be retrieved after the "
                "sub-block is defined.")
        from paddle_tpu.executor import Executor
        from paddle_tpu.core_shim import CPUPlace

        exe = Executor(CPUPlace())
        program = self.sub_program
        startup = self._startup
        src_names = [v.name for v in self.source_vars]
        sinks = list(self.sink_var_names)
        reader = self.underlying_reader

        def batches():
            if isinstance(reader, PyReader):
                # a PyReader pumps dicts keyed by its own var names; remap
                # positionally onto the sub-block sources
                reader.start()
                while True:
                    fd = reader.next_feed()
                    if fd is None:
                        return
                    yield [fd[n] for n in reader.var_names]
            else:
                for batch in (reader() if callable(reader) else reader):
                    yield batch

        def transformed():
            # parameters created inside block() live in the sub-block's
            # startup program; initialize them once
            exe.run(startup)
            for batch in batches():
                feed = dict(zip(src_names, batch))
                yield tuple(exe.run(program, feed=feed, fetch_list=sinks))

        return transformed
