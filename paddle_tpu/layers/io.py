"""Data-entry layers (reference: python/paddle/fluid/layers/io.py — data:39)."""

from paddle_tpu.framework import default_main_program
from paddle_tpu.core.types import VarType


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True, type=VarType.LOD_TENSOR):
    """Declare a feed variable (reference: layers/io.py:39). With
    ``append_batch_size`` a -1 batch dim is prepended, exactly like the
    reference."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = default_main_program().current_block()
    if name in block.vars:
        return block.vars[name]
    return block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        stop_gradient=stop_gradient,
        type=type,
    )
