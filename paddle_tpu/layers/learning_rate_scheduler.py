"""LR schedulers, implemented as ops in the program like the reference
(reference: python/paddle/fluid/layers/learning_rate_scheduler.py — "the
decay is computed by ops in the program itself"). A persistable global step
counter is incremented each run; the decayed LR is a recomputed var read by
the optimizer ops."""

import math

from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.initializer import ConstantInitializer
from paddle_tpu.layers import tensor

__all__ = [
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "noam_decay",
    "cosine_decay",
    "linear_lr_warmup",
]


def _global_step_counter():
    helper = LayerHelper("global_step_counter")
    counter = helper.main_program.global_block().vars.get(
        "@LR_DECAY_COUNTER@"
    )
    if counter is None:
        counter = helper.create_global_variable(
            name="@LR_DECAY_COUNTER@", shape=[1], dtype="float32",
            persistable=True,
        )
        helper.set_variable_initializer(counter, ConstantInitializer(0.0))
        helper.append_op(
            type="increment",
            inputs={"X": [counter]},
            outputs={"Out": [counter]},
            attrs={"step": 1.0},
        )
    return counter


def _unary_expr(fn_op_type, x, **attrs):
    helper = LayerHelper(fn_op_type)
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(
        type=fn_op_type, inputs={"X": [x]}, outputs={"Out": [out]},
        attrs=attrs,
    )
    return out


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step_counter()
    div = _unary_expr("scale", step, scale=1.0 / decay_steps)
    if staircase:
        div = _unary_expr("floor", div)
    # lr * decay_rate^div == lr * exp(div * ln(decay_rate))
    expo = _unary_expr("scale", div, scale=math.log(decay_rate))
    factor = _unary_expr("exp", expo)
    return _unary_expr("scale", factor, scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step_counter()
    div = _unary_expr("scale", step, scale=1.0 / decay_steps)
    if staircase:
        div = _unary_expr("floor", div)
    expo = _unary_expr("scale", div, scale=-decay_rate)
    factor = _unary_expr("exp", expo)
    return _unary_expr("scale", factor, scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from paddle_tpu.layers.nn import elementwise_div

    step = _global_step_counter()
    div = _unary_expr("scale", step, scale=1.0 / decay_steps)
    if staircase:
        div = _unary_expr("floor", div)
    denom = _unary_expr("scale", div, scale=decay_rate, bias=1.0)
    lr = tensor.fill_constant([1], "float32", float(learning_rate))
    return elementwise_div(lr, denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    from paddle_tpu.layers.nn import (
        elementwise_div, elementwise_pow, elementwise_mul, elementwise_add,
        elementwise_min,
    )

    step = _global_step_counter()
    decay_steps_var = tensor.fill_constant([1], "float32", float(decay_steps))
    if cycle:
        ratio = elementwise_div(step, decay_steps_var)
        ceil_r = _unary_expr("ceil", ratio)
        # div_res = max(ceil(step/decay_steps), 1)
        one = tensor.fill_constant([1], "float32", 1.0)
        from paddle_tpu.layers.nn import elementwise_max

        div_res = elementwise_max(ceil_r, one)
        decay_steps_var = elementwise_mul(decay_steps_var, div_res)
        cur = step
    else:
        cur = _unary_expr(
            "clip", step, min=0.0, max=float(decay_steps)
        )
    frac = elementwise_div(cur, decay_steps_var)
    one_minus = _unary_expr("scale", frac, scale=-1.0, bias=1.0)
    powv = tensor.fill_constant([1], "float32", float(power))
    poly = elementwise_pow(one_minus, powv)
    range_lr = _unary_expr(
        "scale", poly, scale=float(learning_rate) - float(end_learning_rate),
        bias=float(end_learning_rate),
    )
    return range_lr


def piecewise_decay(boundaries, values):
    """Piecewise-constant LR: sum of indicator-masked values."""
    from paddle_tpu.layers.nn import sum as sum_layer

    assert len(values) == len(boundaries) + 1
    step = _global_step_counter()
    pieces = []
    prev_b = None
    for i, v in enumerate(values):
        lo = -1.0 if i == 0 else float(boundaries[i - 1])
        hi = float(boundaries[i]) if i < len(boundaries) else 1e30
        # indicator(lo < step <= hi) * v, computed with clips
        # in01 = clip(step - lo, 0, 1) * (1 - clip(step - hi, 0, 1))
        above_lo = _unary_expr("clip", _unary_expr("scale", step, scale=1.0, bias=-lo - 0.5), min=0.0, max=1.0)
        below_hi = _unary_expr("clip", _unary_expr("scale", step, scale=-1.0, bias=hi + 0.5), min=0.0, max=1.0)
        from paddle_tpu.layers.nn import elementwise_mul

        ind = elementwise_mul(above_lo, below_hi)
        pieces.append(_unary_expr("scale", ind, scale=float(v)))
    return sum_layer(pieces)


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (reference: learning_rate_scheduler.py noam_decay)."""
    from paddle_tpu.layers.nn import elementwise_min

    step = _global_step_counter()
    safe_step = _unary_expr("clip", step, min=1.0, max=1e30)
    a = _unary_expr("rsqrt", safe_step)
    b = _unary_expr("scale", step, scale=float(warmup_steps) ** -1.5)
    m = elementwise_min(a, b)
    return _unary_expr("scale", m, scale=float(d_model) ** -0.5)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _global_step_counter()
    epoch = _unary_expr("floor", _unary_expr("scale", step, scale=1.0 / step_each_epoch))
    inner = _unary_expr("scale", epoch, scale=math.pi / epochs)
    cosv = _unary_expr("cos", inner)
    return _unary_expr(
        "scale", cosv, scale=0.5 * float(learning_rate),
        bias=0.5 * float(learning_rate), bias_after_scale=True,
    )


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    from paddle_tpu.layers.nn import elementwise_add, elementwise_mul

    step = _global_step_counter()
    frac = _unary_expr(
        "clip",
        _unary_expr("scale", step, scale=1.0 / float(warmup_steps)),
        min=0.0, max=1.0,
    )
    warm = _unary_expr(
        "scale", frac, scale=float(end_lr) - float(start_lr),
        bias=float(start_lr),
    )
    if isinstance(learning_rate, float):
        after = tensor.fill_constant([1], "float32", learning_rate)
    else:
        after = learning_rate
    # blend: frac<1 -> warm, else after. Use indicator on step>=warmup.
    done = _unary_expr(
        "clip",
        _unary_expr("scale", step, scale=1.0,
                    bias=-float(warmup_steps) + 0.5),
        min=0.0, max=1.0,
    )
    not_done = _unary_expr("scale", done, scale=-1.0, bias=1.0)
    return elementwise_add(
        elementwise_mul(warm, not_done), elementwise_mul(after, done)
    )


def append_LARS(params_grads, learning_rate, weight_decay):
    """Layer-wise adaptive rate scaling applied as per-param learning
    rates (reference: layers/learning_rate_scheduler.py:310 — sets each
    param's optimize_attr['learning_rate'] to
    lr * ||w|| / (||g|| + weight_decay * ||w||))."""
    from paddle_tpu.layers import nn as nn_layers
    from paddle_tpu.layers import ops as ops_layers

    def _balanced_weight(param_norm, grad_norm):
        if weight_decay == 1.0:
            return grad_norm + param_norm
        return grad_norm + weight_decay * param_norm

    for param, grad in params_grads:
        param_lr = param.optimize_attr.get("learning_rate", 1.0)
        param_norm = ops_layers.sqrt(
            nn_layers.reduce_sum(input=ops_layers.square(param)))
        grad_norm = ops_layers.sqrt(
            nn_layers.reduce_sum(input=ops_layers.square(grad)))
        if isinstance(param_lr, float) and param_lr == 1.0:
            decayed_lr = learning_rate * param_norm / _balanced_weight(
                param_norm, grad_norm)
        else:
            decayed_lr = (learning_rate * param_lr * param_norm
                          / _balanced_weight(param_norm, grad_norm))
        param.optimize_attr["learning_rate"] = decayed_lr
