"""Structured control-flow layers: While, StaticRNN, Switch, tensor arrays.

Reference: python/paddle/fluid/layers/control_flow.py (While:687,
StaticRNN:317, Switch:1108, array_write/array_read/array_length,
increment:1022, less_than). The builder API is kept; the execution story is
TPU-native: sub-blocks trace into the same XLA computation as
``lax.while_loop`` / ``lax.scan`` / branch-select (see
paddle_tpu/ops/controlflow_ops.py) instead of nested interpreters over kid
scopes (reference: operators/controlflow/while_op.cc StepScopes).
"""

import contextlib

from paddle_tpu import unique_name
from paddle_tpu.framework import default_main_program, Variable
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.core.types import convert_np_dtype_to_dtype_


def _resolvable_in_ancestors(program, sub_block, name):
    """True if ``name`` resolves in a block strictly above ``sub_block``."""
    b = sub_block
    while b.parent_idx != -1:
        b = program.block(b.parent_idx)
        if name in b.vars:
            return True
    return False


def _analyze_sub_block(program, sub_block):
    """Ordered external reads and external writes of a sub-block.

    External = resolved from an ancestor block (parameters, loop state,
    arrays), not created locally in the sub-block.
    """
    reads, writes = [], []
    read_set, write_set = set(), set()
    written = set()
    for op in sub_block.desc.ops:
        for n in op.input_arg_names():
            if (
                n
                and n not in written
                and n not in sub_block.vars
                and n not in read_set
                and _resolvable_in_ancestors(program, sub_block, n)
            ):
                reads.append(n)
                read_set.add(n)
        for n in op.output_arg_names():
            written.add(n)
            if (
                n
                and n not in sub_block.vars
                and n not in write_set
                and _resolvable_in_ancestors(program, sub_block, n)
            ):
                writes.append(n)
                write_set.add(n)
    return reads, writes


class While:
    """``with While(cond).block():`` — loop while ``cond`` (bool [1]) is true.

    Everything written to an ancestor-block var inside the block is loop-
    carried; such vars (including ``cond``) must be initialized before the
    loop (reference: layers/control_flow.py:687).
    """

    def __init__(self, cond, is_test=False, name=None):
        if not isinstance(cond, Variable):
            raise TypeError("While cond must be a Variable")
        self.cond_var = cond
        self.helper = LayerHelper("while", name=name)

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent_block = program.current_block()
        sub_block = program.create_block()
        try:
            yield
        finally:
            program.rollback()

        reads, writes = _analyze_sub_block(program, sub_block)
        out_names = [n for n in writes if n != self.cond_var.name]
        # every loop-carried output needs its initial value in X, plus all
        # read-only externals
        x_names = list(dict.fromkeys(reads + out_names))

        step_scopes = parent_block.create_var(
            name=unique_name.generate("while_step_scopes"))
        parent_block.append_op(
            type="while",
            inputs={"X": x_names, "Condition": [self.cond_var.name]},
            outputs={"Out": out_names + [self.cond_var.name],
                     "StepScopes": [step_scopes.name]},
            attrs={"sub_block": sub_block.desc.idx, "is_test": False},
        )


class StaticRNN:
    """Time-major recurrence builder lowered to one differentiable
    ``lax.scan`` (reference: layers/control_flow.py StaticRNN:317 →
    operators/recurrent_op.cc).

    Inputs fed via ``step_input`` must be [T, ...] (time-major)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._inputs = []      # (parent_var, sub_var)
        self._memories = []    # (init_parent_var, mem_sub_var)
        self._mem_updates = {}  # mem sub name -> updated var name
        self._step_outputs = []  # sub-block vars
        self._outputs = []       # parent stacked vars
        self._sub_block = None
        self._parent_block = None
        self._complete = False
        self._seq_len = None

    @contextlib.contextmanager
    def step(self):
        program = self.helper.main_program
        self._parent_block = program.current_block()
        self._sub_block = program.create_block()
        try:
            yield
        finally:
            program.rollback()
            self._complete_op()

    def step_input(self, x):
        if x.shape is None or len(x.shape) < 1:
            raise ValueError("step_input must have a time-major shape [T,...]")
        if self._seq_len is None:
            self._seq_len = x.shape[0]
        sub = self.helper.main_program.current_block()
        ipt = sub.create_var(
            name=unique_name.generate("rnn_input"),
            shape=list(x.shape[1:]),
            dtype=x.dtype,
        )
        self._inputs.append((x, ipt))
        return ipt

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1, dtype="float32"):
        # the batch-dim indices parameterize which axes carry the batch in
        # init vs batch_ref (reference: layers/control_flow.py
        # StaticRNN.memory); the padded batch-major representation fixes
        # both at 0/1's defaults, so other values are rejected
        if (init_batch_dim_idx, ref_batch_dim_idx) != (0, 1):
            raise NotImplementedError(
                "StaticRNN.memory: only init_batch_dim_idx=0, "
                "ref_batch_dim_idx=1 (batch-major padded form)")
        from paddle_tpu.layers import tensor as tensor_layers

        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError(
                    "memory needs either init= or (shape= and batch_ref=)")
            # build the init var in the PARENT block
            prog = self.helper.main_program
            cur = prog.current_block_idx
            prog.current_block_idx = self._parent_block.idx
            try:
                init = tensor_layers.fill_constant_batch_size_like(
                    input=batch_ref, shape=[-1] + list(shape),
                    dtype=dtype, value=init_value)
            finally:
                prog.current_block_idx = cur
        sub = self.helper.main_program.current_block()
        mem = sub.create_var(
            name=unique_name.generate("rnn_memory"),
            shape=list(init.shape) if init.shape else None,
            dtype=init.dtype,
        )
        self._memories.append((init, mem))
        return mem

    def update_memory(self, mem, var):
        self._mem_updates[mem.name] = var.name

    def step_output(self, o):
        self._step_outputs.append(o)
        out = self._parent_block.create_var(
            name=unique_name.generate("rnn_output"),
            shape=([self._seq_len] + list(o.shape)) if o.shape is not None
            else None,
            dtype=o.dtype,
        )
        self._outputs.append(out)
        return out

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete_op(self):
        if self._complete:
            return
        self._complete = True
        program = self.helper.main_program
        sub = self._sub_block
        parent = self._parent_block

        reads, _ = _analyze_sub_block(program, sub)
        input_names = {i.name for _, i in self._inputs}
        mem_names = {m.name for _, m in self._memories}
        params = [
            n for n in reads
            if n not in input_names and n not in mem_names
            and n not in {x.name for x, _ in self._inputs}
            and n not in {iv.name for iv, _ in self._memories}
        ]

        finals = [
            parent.create_var(
                name=unique_name.generate("rnn_final_state"),
                shape=list(iv.shape) if iv.shape else None, dtype=iv.dtype)
            for iv, _ in self._memories
        ]
        for m, _ in zip((m for _, m in self._memories), finals):
            if m.name not in self._mem_updates:
                raise RuntimeError(
                    "StaticRNN memory %r was never update_memory()'d" % m.name)

        parent.append_op(
            type="recurrent",
            inputs={
                "Inputs": [x.name for x, _ in self._inputs],
                "InitStates": [iv.name for iv, _ in self._memories],
                "Params": params,
            },
            outputs={
                "Outputs": [o.name for o in self._outputs],
                "FinalStates": [f.name for f in finals],
            },
            attrs={
                "sub_block": sub.desc.idx,
                "input_vars": [i.name for _, i in self._inputs],
                "ex_state_vars": [m.name for _, m in self._memories],
                "state_vars": [
                    self._mem_updates[m.name] for _, m in self._memories
                ],
                "output_vars": [o.name for o in self._step_outputs],
            },
        )

    def __call__(self):
        if len(self._outputs) == 1:
            return self._outputs[0]
        return list(self._outputs)


class Switch:
    """``with switch.case(cond):`` cascade; each case body's writes take
    effect only when its condition is the first true one (reference:
    layers/control_flow.py Switch:1108, used by LR schedulers). Written vars
    must be pre-initialized (their value when no case matches)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._prev_conds = []

    # ``with layers.Switch() as switch:`` form (reference usage in LR
    # schedulers and the contrib decoder)
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        return False

    @contextlib.contextmanager
    def _guarded_block(self, cond_var):
        program = self.helper.main_program
        parent_block = program.current_block()
        sub_block = program.create_block()
        try:
            yield
        finally:
            program.rollback()
        reads, writes = _analyze_sub_block(program, sub_block)
        x_names = list(dict.fromkeys(reads + writes))
        scope_var = parent_block.create_var(
            name=unique_name.generate("cond_scope"))
        parent_block.append_op(
            type="conditional_block",
            inputs={"Cond": [cond_var.name], "Input": x_names},
            outputs={"Out": writes, "Scope": [scope_var.name]},
            attrs={"sub_block": sub_block.desc.idx},
        )

    def case(self, condition):
        from paddle_tpu.layers import nn as nn_layers

        not_prev = None
        for c in self._prev_conds:
            nc = nn_layers.logical_not(c)
            not_prev = nc if not_prev is None else nn_layers.logical_and(
                not_prev, nc)
        self._prev_conds.append(condition)
        eff = condition if not_prev is None else nn_layers.logical_and(
            condition, not_prev)
        return self._guarded_block(eff)

    def default(self):
        from paddle_tpu.layers import nn as nn_layers

        assert self._prev_conds, "default() requires at least one case"
        not_prev = None
        for c in self._prev_conds:
            nc = nn_layers.logical_not(c)
            not_prev = nc if not_prev is None else nn_layers.logical_and(
                not_prev, nc)
        return self._guarded_block(not_prev)


# -- tensor array + loop utility layers ------------------------------------

def create_array(dtype="float32", capacity=None):
    """LoDTensorArray-equivalent: fixed-capacity stacked buffer
    (reference: layers/control_flow.py create_array)."""
    helper = LayerHelper("create_array")
    arr = helper.block.create_var(
        name=unique_name.generate("array"), dtype=dtype)
    attrs = {}
    if capacity is not None:
        attrs["capacity"] = int(capacity)
    helper.append_op(
        type="create_array", inputs={}, outputs={"Out": [arr.name]},
        attrs=attrs)
    arr._array_capacity = capacity
    return arr


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(dtype=x.dtype)
    attrs = {}
    cap = getattr(array, "_array_capacity", None)
    if cap is not None:
        attrs["capacity"] = int(cap)
    helper.append_op(
        type="write_to_array",
        inputs={"X": [x.name], "I": [i.name], "Array": [array.name]},
        outputs={"Out": [array.name]},
        attrs=attrs,
    )
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.block.create_var(
        name=unique_name.generate("array_read"), dtype=array.dtype)
    helper.append_op(
        type="read_from_array",
        inputs={"X": [array.name], "I": [i.name]},
        outputs={"Out": [out.name]},
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.block.create_var(
        name=unique_name.generate("array_len"), shape=[1], dtype="int64")
    helper.append_op(
        type="lod_array_length",
        inputs={"X": [array.name]},
        outputs={"Out": [out.name]},
    )
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.block.create_var(
            name=unique_name.generate("increment"),
            shape=list(x.shape) if x.shape else None, dtype=x.dtype)
    helper.append_op(
        type="increment",
        inputs={"X": [x.name]},
        outputs={"Out": [out.name]},
        attrs={"step": float(value)},
    )
    return out


class DynamicRNN:
    """Variable-length recurrence (reference: layers/control_flow.py
    DynamicRNN → lod_rank_table + shrink-memory machinery). TPU-native:
    inputs are the padded batch-major [B, T, D] + a [B] length tensor, and
    the whole RNN lowers to ONE masked ``lax.scan`` — rows freeze their
    state and emit zeros once t >= length, which is numerically identical
    to the reference's shrinking-batch reordering without any data-
    dependent shapes.

    Divergence from the reference API: the sequence length is passed
    explicitly to ``step_input`` (the reference reads it from the
    LoDTensor's metadata, which does not exist device-side here).
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self._inputs = []
        self._memories = []
        self._mem_updates = {}
        self._step_outputs = []
        self._outputs = []
        self._sub_block = None
        self._parent_block = None
        self._max_len = None
        self._length_var = None
        self._complete = False

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        self._parent_block = program.current_block()
        self._sub_block = program.create_block()
        try:
            yield
        finally:
            program.rollback()
            self._complete_op()

    def step_input(self, x, length=None, level=0):
        """x: padded [B, T, ...]; length: [B] int lengths (required on the
        first step_input)."""
        if x.shape is None or len(x.shape) < 2:
            raise ValueError("DynamicRNN step_input needs [B, T, ...]")
        if self._max_len is None:
            self._max_len = x.shape[1]
        if length is not None:
            self._length_var = length
        if self._length_var is None:
            raise ValueError(
                "DynamicRNN needs the sequence lengths: pass length= on "
                "the first step_input (the padded-batch LoD equivalent)")
        sub = self.helper.main_program.current_block()
        ipt = sub.create_var(
            name=unique_name.generate("drnn_input"),
            shape=[x.shape[0]] + list(x.shape[2:]),
            dtype=x.dtype,
        )
        self._inputs.append((x, ipt))
        return ipt

    def static_input(self, x):
        """Per-sequence constant visible at every step (reference:
        DynamicRNN.static_input). Ancestor-block reads are captured as
        scan-invariant params automatically, so the var is used as-is."""
        return x

    def memory(self, init=None, shape=None, value=0.0, dtype="float32",
               need_reorder=False):
        from paddle_tpu.layers import tensor as tensor_layers

        if init is None:
            if shape is None or not self._inputs:
                raise ValueError(
                    "memory needs init= or shape= after a step_input")
            prog = self.helper.main_program
            cur = prog.current_block_idx
            prog.current_block_idx = self._parent_block.idx
            try:
                init = tensor_layers.fill_constant_batch_size_like(
                    input=self._inputs[0][0], shape=[-1] + list(shape),
                    dtype=dtype, value=value)
            finally:
                prog.current_block_idx = cur
        sub = self.helper.main_program.current_block()
        mem = sub.create_var(
            name=unique_name.generate("drnn_memory"),
            shape=list(init.shape) if init.shape else None,
            dtype=init.dtype,
        )
        self._memories.append((init, mem))
        return mem

    def update_memory(self, ex_mem, new_mem):
        self._mem_updates[ex_mem.name] = new_mem.name

    def output(self, *outputs):
        for o in outputs:
            self._step_outputs.append(o)
            out = self._parent_block.create_var(
                name=unique_name.generate("drnn_output"),
                shape=([o.shape[0], self._max_len] + list(o.shape[1:]))
                if o.shape is not None else None,
                dtype=o.dtype,
            )
            self._outputs.append(out)

    def _complete_op(self):
        if self._complete:
            return
        self._complete = True
        program = self.helper.main_program
        sub = self._sub_block
        parent = self._parent_block

        reads, _ = _analyze_sub_block(program, sub)
        input_names = {i.name for _, i in self._inputs}
        mem_names = {m.name for _, m in self._memories}
        params = [
            n for n in reads
            if n not in input_names and n not in mem_names
            and n not in {x.name for x, _ in self._inputs}
            and n not in {iv.name for iv, _ in self._memories}
        ]
        finals = [
            parent.create_var(
                name=unique_name.generate("drnn_final_state"),
                shape=list(iv.shape) if iv.shape else None, dtype=iv.dtype)
            for iv, _ in self._memories
        ]
        for _, m in self._memories:
            if m.name not in self._mem_updates:
                raise RuntimeError(
                    "DynamicRNN memory %r was never update_memory()'d"
                    % m.name)
        parent.append_op(
            type="recurrent",
            inputs={
                "Inputs": [x.name for x, _ in self._inputs],
                "InitStates": [iv.name for iv, _ in self._memories],
                "Params": params,
                "SeqLen": [self._length_var.name],
            },
            outputs={
                "Outputs": [o.name for o in self._outputs],
                "FinalStates": [f.name for f in finals],
            },
            attrs={
                "sub_block": sub.desc.idx,
                "time_major": False,
                "input_vars": [i.name for _, i in self._inputs],
                "ex_state_vars": [m.name for _, m in self._memories],
                "state_vars": [
                    self._mem_updates[m.name] for _, m in self._memories
                ],
                "output_vars": [o.name for o in self._step_outputs],
            },
        )

    def __call__(self):
        if len(self._outputs) == 1:
            return self._outputs[0]
        return list(self._outputs)


class IfElse:
    """Per-row branching (reference: layers/control_flow.py IfElse:1490 →
    conditional_block pairs with split/merge by a [B, 1] bool mask).

    TPU-native: both branches trace over the FULL batch and each output
    pair merges with a row-wise select — the XLA-friendly form of the
    reference's split_lod_tensor/merge_lod_tensor. Identical results for
    the per-row computations IfElse exists for; a batch-global reduction
    inside a branch would see all rows (the reference sees only its
    subset) — compute such reductions outside the branch.
    """

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self._outputs = {True: [], False: []}
        self._in_branch = None

    @contextlib.contextmanager
    def true_block(self):
        self._in_branch = True
        try:
            yield
        finally:
            self._in_branch = None

    @contextlib.contextmanager
    def false_block(self):
        self._in_branch = False
        try:
            yield
        finally:
            self._in_branch = None

    def input(self, x):
        assert self._in_branch is not None, "input() only inside a block"
        return x

    def output(self, *outs):
        assert self._in_branch is not None, "output() only inside a block"
        self._outputs[self._in_branch].extend(outs)

    def __call__(self):
        t_outs, f_outs = self._outputs[True], self._outputs[False]
        if len(t_outs) != len(f_outs):
            raise ValueError(
                "IfElse branches declared different output counts: "
                "%d vs %d" % (len(t_outs), len(f_outs)))
        merged = []
        block = self.helper.block
        for tv, fv in zip(t_outs, f_outs):
            out = block.create_var(
                name=unique_name.generate("ifelse_out"),
                shape=list(tv.shape) if tv.shape else None,
                dtype=tv.dtype)
            self.helper.append_op(
                type="where",
                inputs={"Condition": [self.cond.name], "X": [tv.name],
                        "Y": [fv.name]},
                outputs={"Out": [out.name]})
            merged.append(out)
        return merged
