"""NN layers (reference: python/paddle/fluid/layers/nn.py, 10k LoC with
~200 layer functions — fc:193, embedding:302, conv2d:1792, batch_norm:2753,
layer_norm:3070, matmul:4581, softmax_with_cross_entropy:5659...)."""

from paddle_tpu.framework import Variable
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.initializer import ConstantInitializer, NormalInitializer

__all__ = [
    "fc",
    "tree_conv",
    "embedding",
    "conv2d",
    "depthwise_conv2d",
    "conv2d_transpose",
    "pool2d",
    "batch_norm",
    "sync_batch_norm",
    "layer_norm",
    "group_norm",
    "dropout",
    "softmax",
    "log_softmax",
    "matmul",
    "mul",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "reshape",
    "transpose",
    "split",
    "squeeze",
    "unsqueeze",
    "stack",
    "unstack",
    "expand",
    "slice",
    "gather",
    "scatter",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "topk",
    "one_hot",
    "l2_normalize",
    "label_smooth",
    "pad",
    "pad2d",
    "lrn",
    "relu",
    "prelu",
    "leaky_relu",
    "maxout",
    "image_resize",
    "resize_bilinear",
    "resize_nearest",
    "clip",
    "clip_by_norm",
    "mean",
    "shape",
    "sequence_pool",
    "sequence_softmax",
    "sequence_expand",
    "sequence_mask",
    "sequence_reverse",
    "sequence_concat",
    "sequence_slice",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_expand_as",
    "sequence_pad",
    "sequence_unpad",
    "sequence_conv",
    "sequence_enumerate",
    "scale",
    "sum",
    "cumsum",
    "dot_product_attention",
    "where",
    "equal",
    "less_than",
    "greater_than",
    "not_equal",
    "less_equal",
    "greater_equal",
    "logical_and",
    "logical_or",
    "logical_xor",
    "logical_not",
    "dynamic_lstm",
    "dynamic_gru",
    "beam_search",
    "beam_search_decode",
    "flatten",
    "cos_sim",
    "affine_channel",
    "shuffle_channel",
    "space_to_depth",
    "crop",
    "pad_constant_like",
    "multiplex",
    "bilinear_tensor_product",
    "rank_loss",
    "margin_rank_loss",
    "bpr_loss",
    "teacher_student_sigmoid_loss",
    "dice_loss",
    "mean_iou",
    "sampling_id",
    "random_crop",
    "add_position_encoding",
    "hash",
    "row_conv",
    "grid_sampler",
    "affine_grid",
    "ctc_greedy_decoder",
    "lstm_unit",
    "gru_unit",
    "gaussian_random",
    "selu",
    "has_inf",
    "has_nan",
    "isfinite",
    "is_empty",
    "conv3d",
    "conv3d_transpose",
    "pool3d",
    "adaptive_pool2d",
    "image_resize_short",
    "linear_chain_crf",
    "crf_decoding",
    "nce",
    "hsigmoid",
    "sequence_reshape",
    "sequence_scatter",
    "lod_reset",
    "data_norm",
    "pow",
    "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like",
    "autoincreased_step_counter",
    "create_parameter",
    "im2sequence",
    "Print",
    "tensor_array_to_tensor",
    "adaptive_pool3d",
    "merge_selected_rows",
    "get_tensor_from_selected_rows",
    "dynamic_lstmp",
    "lstm",
    "psroi_pool",
    "chunk_eval",
    "py_func",
    "load",
    "reorder_lod_tensor_by_rank",
    "similarity_focus",
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """Fully-connected layer (reference: layers/nn.py:193): per-input mul ops,
    summed, plus bias and activation."""
    helper = LayerHelper("fc", input=input, name=name, act=act,
                         bias_attr=bias_attr)
    dtype = helper.input_dtype()
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = param_attr if isinstance(param_attr, (list, tuple)) else [
        param_attr
    ] * len(inputs)

    mul_results = []
    for inp, pattr in zip(inputs, param_attrs):
        input_shape = inp.shape
        in_features = 1
        for d in input_shape[num_flatten_dims:]:
            in_features *= d
        w = helper.create_parameter(
            attr=pattr, shape=[in_features, size], dtype=dtype
        )
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="mul",
            inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)

    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            type="sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]}
        )
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """Embedding lookup (reference: layers/nn.py:302). ``is_sparse`` selects
    sparse SelectedRows gradients: ``lookup_table_grad`` emits a
    (rows, values) pytree and the optimizer applies row-wise scatter
    updates — no table-sized gradient is materialized (see
    core/selected_rows.py). ``is_distributed`` additionally shards the
    table across parameter servers via the distribute transpiler's
    lookup-table path."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(
        attr=param_attr, shape=size, dtype=dtype, is_bias=False
    )
    out = helper.create_variable_for_type_inference(dtype)
    padding_idx = (
        -1 if padding_idx is None
        else padding_idx if padding_idx >= 0
        else size[0] + padding_idx
    )
    helper.append_op(
        type="lookup_table",
        inputs={"Ids": [input], "W": [w]},
        outputs={"Out": [out]},
        attrs={
            "is_sparse": is_sparse,
            "is_distributed": is_distributed,
            "padding_idx": padding_idx,
        },
    )
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None):
    """2-D convolution, NCHW (reference: layers/nn.py:1792)."""
    helper = LayerHelper("conv2d", name=name, act=act, bias_attr=bias_attr)
    dtype = input.dtype
    num_channels = input.shape[1]
    if groups is None:
        groups = 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]

    filter_shape = [num_filters, num_channels // groups] + list(filter_size)

    def _default_weight_init():
        import math

        fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
        std = math.sqrt(2.0 / fan_in)
        return NormalInitializer(0.0, std)

    w = helper.create_parameter(
        attr=param_attr,
        shape=filter_shape,
        dtype=dtype,
        default_initializer=_default_weight_init(),
    )
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "use_cudnn": use_cudnn,
        },
    )
    pre_act = _conv_bias(helper, pre_bias)
    return helper.append_activation(pre_act)


def _conv_bias(helper, pre_bias):
    bias_attr = helper.kwargs.get("bias_attr")
    if bias_attr is False:
        return pre_bias
    num_filters = pre_bias.shape[1]
    bias = helper.create_parameter(
        bias_attr if bias_attr not in (None, True) else ParamAttr(),
        shape=[num_filters],
        dtype=pre_bias.dtype,
        is_bias=True,
    )
    out = helper.create_variable_for_type_inference(dtype=pre_bias.dtype)
    helper.append_op(
        type="elementwise_add",
        inputs={"X": [pre_bias], "Y": [bias]},
        outputs={"Out": [out]},
        attrs={"axis": 1},
    )
    return out


def depthwise_conv2d(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, param_attr=None, bias_attr=None, act=None,
                     name=None):
    return conv2d(input, num_filters, filter_size, stride, padding, dilation,
                  groups=input.shape[1], param_attr=param_attr,
                  bias_attr=bias_attr, act=act, name=name)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv2d_transpose", name=name, act=act,
                         bias_attr=bias_attr)
    dtype = input.dtype
    num_channels = input.shape[1]
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    filter_shape = [num_channels, num_filters // (groups or 1)] + list(filter_size)
    w = helper.create_parameter(attr=param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups or 1,
        },
    )
    pre_act = _conv_bias(helper, pre_bias)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    helper = LayerHelper("pool2d", name=name)
    if isinstance(pool_size, int):
        pool_size = [pool_size, pool_size]
    if isinstance(pool_stride, int):
        pool_stride = [pool_stride, pool_stride]
    if isinstance(pool_padding, int):
        pool_padding = [pool_padding, pool_padding]
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": pool_size,
            "strides": pool_stride,
            "paddings": pool_padding,
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        },
    )
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False,
               fuse_with_relu=False, use_global_stats=False):
    """Batch normalization (reference: layers/nn.py:2753) with persistable
    moving mean/variance updated in-program."""
    return _batch_norm_layer(
        "batch_norm", input, act=act, is_test=is_test, momentum=momentum,
        epsilon=epsilon, param_attr=param_attr, bias_attr=bias_attr,
        data_layout=data_layout, name=name,
        moving_mean_name=moving_mean_name,
        moving_variance_name=moving_variance_name,
        use_global_stats=use_global_stats)


def sync_batch_norm(input, act=None, is_test=False, momentum=0.9,
                    epsilon=1e-5, param_attr=None, bias_attr=None,
                    data_layout="NCHW", name=None, moving_mean_name=None,
                    moving_variance_name=None, use_global_stats=False):
    """Cross-replica batch normalization (reference: sync_batch_norm_op):
    batch statistics are computed over the GLOBAL batch — every data-
    parallel shard contributes to the mean/variance via one psum each.
    Under GSPMD that is batch_norm's semantics already (the partitioner
    derives the collectives from the batch sharding), so this layer only
    stamps the distinct op type for program-level tooling."""
    return _batch_norm_layer(
        "sync_batch_norm", input, act=act, is_test=is_test,
        momentum=momentum, epsilon=epsilon, param_attr=param_attr,
        bias_attr=bias_attr, data_layout=data_layout, name=name,
        moving_mean_name=moving_mean_name,
        moving_variance_name=moving_variance_name,
        use_global_stats=use_global_stats)


def _batch_norm_layer(op_type, input, act=None, is_test=False, momentum=0.9,
                      epsilon=1e-5, param_attr=None, bias_attr=None,
                      data_layout="NCHW", name=None, moving_mean_name=None,
                      moving_variance_name=None, use_global_stats=False):
    helper = LayerHelper(op_type, name=name, act=act)
    dtype = input.dtype
    if data_layout == "NCHW":
        channel_num = input.shape[1]
    else:
        channel_num = input.shape[-1]
    param_shape = [channel_num]

    scale = helper.create_parameter(
        attr=param_attr, shape=param_shape, dtype=dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        attr=bias_attr, shape=param_shape, dtype=dtype, is_bias=True,
    )

    from paddle_tpu import unique_name

    mean = helper.create_global_variable(
        name=moving_mean_name or unique_name.generate(helper.name + ".mean"),
        shape=param_shape, dtype=dtype, persistable=True,
    )
    helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    variance = helper.create_global_variable(
        name=moving_variance_name or unique_name.generate(helper.name + ".var"),
        shape=param_shape, dtype=dtype, persistable=True,
    )
    helper.set_variable_initializer(variance, ConstantInitializer(1.0))

    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_variance = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)

    helper.append_op(
        type=op_type,
        inputs={
            "X": [input],
            "Scale": [scale],
            "Bias": [bias],
            "Mean": [mean],
            "Variance": [variance],
        },
        outputs={
            "Y": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_variance],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", name=name, act=act)
    dtype = input.dtype
    input_shape = input.shape
    norm_shape = [int(__import__("numpy").prod(input_shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            attr=param_attr, shape=norm_shape, dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            attr=bias_attr, shape=norm_shape, dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [b]
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean_out], "Variance": [var_out]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis},
    )
    return helper.append_activation(out)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm", name=name, act=act)
    dtype = input.dtype
    channel_num = input.shape[1]
    inputs = {"X": [input]}
    if param_attr is not False:
        s = helper.create_parameter(
            attr=param_attr, shape=[channel_num], dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=bias_attr, shape=[channel_num], dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [b]
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="group_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean_out], "Variance": [var_out]},
        attrs={"groups": groups, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    mask = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                     stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed if seed is not None else 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def softmax(input, use_cudnn=True, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="softmax",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="log_softmax",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={
            "transpose_X": transpose_x,
            "transpose_Y": transpose_y,
            "alpha": float(alpha),
        },
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="mul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def _elementwise_layer(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, name=name, act=act)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(
            type=op_type,
            inputs={"X": [x], "Y": [y]},
            outputs={"Out": [out]},
            attrs={"axis": axis},
        )
        return helper.append_activation(out)

    layer.__name__ = op_type
    return layer


elementwise_add = _elementwise_layer("elementwise_add")
elementwise_sub = _elementwise_layer("elementwise_sub")
elementwise_mul = _elementwise_layer("elementwise_mul")
elementwise_div = _elementwise_layer("elementwise_div")
elementwise_max = _elementwise_layer("elementwise_max")
elementwise_min = _elementwise_layer("elementwise_min")
elementwise_pow = _elementwise_layer("elementwise_pow")


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name, act=act)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    xshape = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                       stop_gradient=True)
    helper.append_op(
        type="reshape2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"shape": list(shape)},
    )
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    xshape = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                       stop_gradient=True)
    helper.append_op(
        type="transpose2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": list(perm)},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
        n_out = num
    else:
        num = 0
        sections = list(num_or_sections)
        n_out = len(sections)
    outs = [
        helper.create_variable_for_type_inference(dtype=input.dtype)
        for _ in range(n_out)
    ]
    helper.append_op(
        type="split",
        inputs={"X": [input]},
        outputs={"Out": outs},
        attrs={"axis": dim, "num": num, "sections": sections},
    )
    return outs


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    xshape = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                       stop_gradient=True)
    helper.append_op(
        type="squeeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": axes},
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    xshape = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                       stop_gradient=True)
    helper.append_op(
        type="unsqueeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": axes},
    )
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    x = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(dtype=x[0].dtype)
    helper.append_op(
        type="stack",
        inputs={"X": x},
        outputs={"Y": [out]},
        attrs={"axis": axis},
    )
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    if num is None:
        num = x.shape[axis]
    outs = [
        helper.create_variable_for_type_inference(dtype=x.dtype)
        for _ in range(num)
    ]
    helper.append_op(
        type="unstack",
        inputs={"X": [x]},
        outputs={"Y": outs},
        attrs={"axis": axis, "num": num},
    )
    return outs


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="expand",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"expand_times": list(expand_times)},
    )
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="slice",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="gather",
        inputs={"X": [input], "Index": [index]},
        outputs={"Out": [out]},
    )
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]},
        attrs={"overwrite": overwrite},
    )
    return out


def _reduce_layer(op_type):
    def layer(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=input.dtype)
        if dim is None:
            dim_attr, reduce_all = [0], True
        else:
            dim_attr = dim if isinstance(dim, (list, tuple)) else [dim]
            reduce_all = False
        helper.append_op(
            type=op_type,
            inputs={"X": [input]},
            outputs={"Out": [out]},
            attrs={"dim": list(dim_attr), "keep_dim": keep_dim,
                   "reduce_all": reduce_all},
        )
        return out

    layer.__name__ = op_type
    return layer


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(dtype=input.dtype)
    indices = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]},
        attrs={"k": k},
    )
    indices.stop_gradient = True
    return values, indices


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(
        type="one_hot",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"depth": depth},
    )
    out.stop_gradient = True
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    norm = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                     stop_gradient=True)
    helper.append_op(
        type="l2_normalize",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [norm]},
        attrs={"axis": axis, "epsilon": epsilon},
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="label_smooth",
        inputs={"X": [label]},
        outputs={"Out": [out]},
        attrs={"epsilon": float(epsilon)},
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="pad",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "pad_value": float(pad_value)},
    )
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="pad2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "mode": mode,
               "pad_value": float(pad_value)},
    )
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    mid = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                    stop_gradient=True)
    helper.append_op(
        type="lrn",
        inputs={"X": [input]},
        outputs={"Out": [out], "MidOut": [mid]},
        attrs={"n": n, "k": k, "alpha": alpha, "beta": beta},
    )
    return out


def relu(x, name=None):
    helper = LayerHelper("relu", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="relu", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [1, x.shape[1], 1, 1]
    else:
        alpha_shape = [1] + list(x.shape[1:])
    alpha = helper.create_parameter(
        attr=param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25),
    )
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="prelu",
        inputs={"X": [x], "Alpha": [alpha]},
        outputs={"Out": [out]},
        attrs={"mode": mode},
    )
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper("leaky_relu", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="leaky_relu",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"alpha": alpha},
    )
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="maxout",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"groups": groups},
    )
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1):
    if actual_shape is not None:
        raise NotImplementedError(
            "image_resize: actual_shape (runtime output shape) is "
            "incompatible with XLA static shapes; pass out_shape/scale")
    if out_shape is None:
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    op_type = "bilinear_interp" if resample == "BILINEAR" else "nearest_interp"
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type=op_type,
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"out_h": int(out_shape[0]), "out_w": int(out_shape[1]),
               "align_corners": bool(align_corners),
               "align_mode": int(align_mode)},
    )
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape=actual_shape,
                        align_corners=align_corners,
                        align_mode=align_mode)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape=actual_shape,
                        align_corners=align_corners)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="clip",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"min": float(min), "max": float(max)},
    )
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="clip_by_norm",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"max_norm": float(max_norm)},
    )
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="shape", inputs={"Input": [input]}, outputs={"Out": [out]}
    )
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={
            "scale": float(scale),
            "bias": float(bias),
            "bias_after_scale": bias_after_scale,
        },
    )
    return helper.append_activation(out)


def sum(x):
    helper = LayerHelper("sum")
    x = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(dtype=x[0].dtype)
    helper.append_op(type="sum", inputs={"X": x}, outputs={"Out": [out]})
    return out


def cumsum(x, axis=None, exclusive=None, reverse=None):
    helper = LayerHelper("cumsum")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    attrs = {}
    if axis is not None:
        attrs["axis"] = axis
    if exclusive is not None:
        attrs["exclusive"] = exclusive
    if reverse is not None:
        attrs["reverse"] = reverse
    helper.append_op(
        type="cumsum", inputs={"X": [x]}, outputs={"Out": [out]}, attrs=attrs
    )
    return out


# -- sequence layers (padded+length representation, see ops/sequence_ops) --
def sequence_pool(input, pool_type, is_test=False, length=None):
    # ``is_test`` only gates the reference kernel's MaxIndex scratch
    # output (sequence_pool_op.cc); the functional lowering derives the
    # backward from the forward, so it needs no flag
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"X": [input]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_pool",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={"pooltype": pool_type.upper()},
    )
    return out


def sequence_softmax(input, use_cudnn=False, name=None, length=None):
    del use_cudnn  # CUDA knob; XLA picks the softmax lowering
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"X": [input]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_softmax", inputs=inputs, outputs={"Out": [out]}
    )
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="sequence_expand",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"ref_level": ref_level},
    )
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(
        type="sequence_mask",
        inputs={"X": [x]},
        outputs={"Y": [out]},
        attrs={"maxlen": maxlen if maxlen is not None else -1},
    )
    return out


def sequence_reverse(x, length=None, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {"X": [x]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_reverse", inputs=inputs, outputs={"Y": [out]}
    )
    return out


def sequence_concat(input, lengths=None, name=None):
    """Per-row concat of ragged sequences (reference: layers/nn.py
    sequence_concat → sequence_concat_op.cc). ``input`` is a list of
    padded [B, T_k, D] tensors, ``lengths`` the matching [B] length
    tensors; the result is left-compacted. The output's lengths are
    elementwise sums of ``lengths`` (compute via elementwise_add)."""
    helper = LayerHelper("sequence_concat", name=name)
    xs = input if isinstance(input, (list, tuple)) else [input]
    out = helper.create_variable_for_type_inference(dtype=xs[0].dtype)
    inputs = {"X": list(xs)}
    if lengths is not None:
        inputs["Length"] = list(lengths)
    helper.append_op(type="sequence_concat", inputs=inputs,
                     outputs={"Out": [out]})
    return out


def sequence_slice(input, offset, length, name=None):
    """Per-row subsequence (reference: layers/nn.py sequence_slice)."""
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="sequence_slice",
        inputs={"X": [input], "Offset": [offset], "Length": [length]},
        outputs={"Out": [out]})
    return out


def sequence_first_step(input, length=None):
    """First timestep of each sequence (reference: layers/nn.py
    sequence_first_step = sequence_pool FIRST)."""
    return sequence_pool(input, "first", length=length)


def sequence_last_step(input, length=None):
    """Last valid timestep of each sequence (reference: layers/nn.py
    sequence_last_step = sequence_pool LAST)."""
    return sequence_pool(input, "last", length=length)


def sequence_expand_as(x, y, name=None):
    """Broadcast x rows along y's time dim (reference: layers/nn.py
    sequence_expand_as)."""
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="sequence_expand_as",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Pad each row to maxlen with pad_value; returns (Out, Length)
    (reference: layers/nn.py sequence_pad)."""
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    len_out = helper.create_variable_for_type_inference(dtype="int64")
    inputs = {"X": [x], "PadValue": [pad_value]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_pad", inputs=inputs,
        outputs={"Out": [out], "Length": [len_out]},
        attrs={"padded_length": maxlen if maxlen is not None else -1})
    return out, len_out


def sequence_unpad(x, length, name=None):
    """Strip pad values back to the zero-padded convention (reference:
    layers/nn.py sequence_unpad)."""
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="sequence_unpad",
                     inputs={"X": [x], "Length": [length]},
                     outputs={"Out": [out]})
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None,
                  length=None, name=None):
    """Context-window convolution over time (reference: layers/nn.py
    sequence_conv → sequence_conv_op.cc)."""
    helper = LayerHelper("sequence_conv", name=name, act=act,
                         bias_attr=bias_attr, param_attr=param_attr)
    dtype = input.dtype
    d = input.shape[-1]
    filter_shape = [filter_size * d, num_filters]
    filter_param = helper.create_parameter(
        attr=param_attr, shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [input], "Filter": [filter_param]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_conv", inputs=inputs, outputs={"Out": [out]},
        attrs={"contextLength": filter_size,
               "contextStart": -((filter_size - 1) // 2),
               "contextStride": filter_stride})
    pre_act = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(pre_act)


def sequence_enumerate(input, win_size, pad_value=0, length=None,
                       name=None):
    """Sliding id windows (reference: layers/nn.py sequence_enumerate);
    ``length`` bounds windows per row like the reference's LoD."""
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"X": [input]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_enumerate", inputs=inputs,
        outputs={"Out": [out]},
        attrs={"win_size": win_size, "pad_value": pad_value})
    return out


def dot_product_attention(querys, keys, values):
    """Scaled dot-product attention built from matmul/softmax layers."""
    import math

    product = matmul(querys, keys, transpose_y=True,
                     alpha=1.0 / math.sqrt(querys.shape[-1]))
    weights = softmax(product)
    return matmul(weights, values), weights


def _cmp_layer(op_type):
    def layer(x, y, force_cpu=None, cond=None):
        del force_cpu  # placement knob; XLA decides
        helper = LayerHelper(op_type)
        if cond is None:
            cond = helper.create_variable_for_type_inference(dtype="bool")
        cond.stop_gradient = True
        helper.append_op(
            type=op_type,
            inputs={"X": [x], "Y": [y]},
            outputs={"Out": [cond]},
        )
        return cond

    layer.__name__ = op_type
    return layer


equal = _cmp_layer("equal")
not_equal = _cmp_layer("not_equal")
less_than = _cmp_layer("less_than")
less_equal = _cmp_layer("less_equal")
greater_than = _cmp_layer("greater_than")
greater_equal = _cmp_layer("greater_equal")


def dynamic_lstm(input, size, h_0=None, c_0=None, seq_len=None,
                 param_attr=None, bias_attr=None, use_peepholes=False,
                 is_reverse=False, gate_activation="sigmoid",
                 cell_activation="tanh", candidate_activation="tanh",
                 dtype="float32", name=None):
    """LSTM over a padded [B, T, 4H] pre-projected input (reference:
    layers/nn.py:370 — the LoD-batched form becomes padded+masked via
    ``seq_len``). Returns (hidden [B,T,H], cell [B,T,H])."""
    helper = LayerHelper("dynamic_lstm", name=name, param_attr=param_attr,
                         bias_attr=bias_attr)
    hidden_size = size // 4
    weight = helper.create_parameter(
        attr=param_attr, shape=[hidden_size, 4 * hidden_size], dtype=dtype)
    n_bias = 7 * hidden_size if use_peepholes else 4 * hidden_size
    bias = helper.create_parameter(
        attr=bias_attr if bias_attr not in (None, True) else None,
        shape=[1, n_bias], dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(
        type="dynamic_lstm",
        inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell]},
        attrs={
            "use_peepholes": use_peepholes,
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "cell_activation": cell_activation,
            "candidate_activation": candidate_activation,
        },
    )
    return hidden, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False,
                seq_len=None, dtype="float32", name=None):
    """GRU over a padded [B, T, 3H] pre-projected input (reference:
    layers/nn.py dynamic_gru). Returns hidden [B, T, H]."""
    helper = LayerHelper("dynamic_gru", name=name, param_attr=param_attr,
                         bias_attr=bias_attr)
    weight = helper.create_parameter(
        attr=param_attr, shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(
        attr=bias_attr if bias_attr not in (None, True) else None,
        shape=[1, 3 * size], dtype=dtype, is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(
        type="dynamic_gru",
        inputs=inputs,
        outputs={"Hidden": [hidden]},
        attrs={
            "is_reverse": is_reverse,
            "gate_activation": gate_activation,
            "activation": candidate_activation,
            "origin_mode": origin_mode,
        },
    )
    return hidden


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=False, first_step=False):
    """One beam-search step (reference: layers/nn.py:3873 — fixed
    batch*beam rows instead of LoD shrinking). ``ids`` optionally maps
    score columns to token ids (None means column index IS the id, the
    common vocab-scores case); ``level`` (the reference's LoD level) is
    meaningless in the padded form; with ``is_accumulated=False`` the
    scores are per-step probabilities and are log-accumulated onto
    pre_scores here, as the reference op does. Returns (selected_ids,
    selected_scores), or a 3-tuple including parent_idx when
    ``return_parent_idx=True``."""
    del level
    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_variable_for_type_inference("int64")
    sel_scores = helper.create_variable_for_type_inference(scores.dtype)
    parent = helper.create_variable_for_type_inference("int64")
    inputs = {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
              "scores": [scores]}
    if ids is not None:
        inputs["ids"] = [ids]
    helper.append_op(
        type="beam_search",
        inputs=inputs,
        outputs={"selected_ids": [sel_ids],
                 "selected_scores": [sel_scores],
                 "parent_idx": [parent]},
        attrs={"beam_size": beam_size, "end_id": end_id,
               "is_accumulated": bool(is_accumulated),
               "first_step": first_step},
    )
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, beam_size, end_id, name=None,
                       parent_array=None):
    """Backtrack a finished beam decode from the step arrays (reference:
    layers beam_search_decode). Returns (sentence_ids [BW, max_len],
    sentence_scores [BW, 1]). The padded representation needs the
    parent-pointer array our beam_search emits (the reference recovers
    parents from LoD; here they are explicit)."""
    ids_array, scores_array = ids, scores
    if parent_array is None:
        raise ValueError(
            "beam_search_decode needs parent_array= (the parent_idx "
            "array collected from beam_search steps); the padded beam "
            "representation stores parent pointers explicitly where the "
            "reference recovers them from LoD")
    helper = LayerHelper("beam_search_decode", name=name)
    sent_ids = helper.create_variable_for_type_inference("int64")
    sent_scores = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids_array], "Scores": [scores_array],
                "ParentIdx": [parent_array]},
        outputs={"sentence_ids": [sent_ids],
                 "sentence_scores": [sent_scores]},
        attrs={"beam_size": beam_size, "end_id": end_id},
    )
    return sent_ids, sent_scores


def _logical_layer(op_type, unary=False):
    def layer(x, y=None, out=None, name=None):
        helper = LayerHelper(op_type, name=name)
        if out is None:
            out = helper.create_variable_for_type_inference(dtype="bool")
        inputs = {"X": [x]}
        if not unary:
            inputs["Y"] = [y]
        helper.append_op(type=op_type, inputs=inputs,
                         outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    return layer


logical_and = _logical_layer("logical_and")
logical_or = _logical_layer("logical_or")
logical_xor = _logical_layer("logical_xor")
logical_not = _logical_layer("logical_not", unary=True)


def where(condition, x, y):
    helper = LayerHelper("where")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="where",
        inputs={"Condition": [condition], "X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


# -- round-2 layer-surface completion (reference: layers/nn.py __all__) ----

def flatten(x, axis=1, name=None):
    """(reference: layers/nn.py flatten) — trailing dims must be static
    (the batch-side dim may be dynamic)."""
    trail = 1
    for d in x.shape[axis:]:
        if d is None or d < 0:
            raise ValueError(
                "flatten needs static dims after axis=%d; got shape %s"
                % (axis, (x.shape,)))
        trail *= d
    return reshape(x, shape=[-1, trail], name=name)


def cos_sim(X, Y):
    """(reference: layers/nn.py cos_sim)"""
    helper = LayerHelper("cos_sim")
    out = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op(type="cos_sim", inputs={"X": [X], "Y": [Y]},
                     outputs={"Out": [out], "XNorm": [xn], "YNorm": [yn]})
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    """(reference: layers/nn.py affine_channel)"""
    helper = LayerHelper("affine_channel", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="affine_channel",
                     inputs={"X": [x], "Scale": [scale], "Bias": [bias]},
                     outputs={"Out": [out]},
                     attrs={"data_layout": data_layout})
    return out


def shuffle_channel(x, group, name=None):
    """(reference: layers/nn.py shuffle_channel)"""
    helper = LayerHelper("shuffle_channel", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="shuffle_channel", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"group": group})
    return out


def space_to_depth(x, blocksize, name=None):
    """(reference: layers/nn.py space_to_depth)"""
    helper = LayerHelper("space_to_depth", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="space_to_depth", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"blocksize": blocksize})
    return out


def crop(x, shape=None, offsets=None, name=None):
    """(reference: layers/nn.py crop)"""
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    attrs = {}
    if hasattr(shape, "name"):
        inputs["Y"] = [shape]
    else:
        attrs["shape"] = list(shape)
    if offsets is not None:
        if hasattr(offsets, "name"):
            inputs["Offsets"] = [offsets]
        else:
            attrs["offsets"] = list(offsets)
    helper.append_op(type="crop", inputs=inputs, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """(reference: layers/nn.py pad_constant_like)"""
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(y.dtype)
    helper.append_op(type="pad_constant_like",
                     inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
                     attrs={"pad_value": float(pad_value)})
    return out


def multiplex(inputs, index):
    """(reference: layers/nn.py multiplex)"""
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]})
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """(reference: layers/nn.py bilinear_tensor_product)"""
    helper = LayerHelper("bilinear_tensor_product", name=name, act=act,
                         bias_attr=bias_attr)
    w = helper.create_parameter(
        attr=param_attr, shape=[size, x.shape[1], y.shape[1]],
        dtype=x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        from paddle_tpu.param_attr import ParamAttr

        bias = helper.create_parameter(
            attr=bias_attr if bias_attr not in (None, True) else ParamAttr(),
            shape=[1, size], dtype=x.dtype, is_bias=True)
        inputs["Bias"] = [bias]
    helper.append_op(type="bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def rank_loss(label, left, right, name=None):
    """(reference: layers/nn.py rank_loss)"""
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="rank_loss",
                     inputs={"Label": [label], "Left": [left],
                             "Right": [right]},
                     outputs={"Out": [out]})
    return out


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """(reference: layers/nn.py margin_rank_loss)"""
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference("float32")
    act = helper.create_variable_for_type_inference("float32")
    helper.append_op(type="margin_rank_loss",
                     inputs={"Label": [label], "X1": [left], "X2": [right]},
                     outputs={"Out": [out], "Activated": [act]},
                     attrs={"margin": margin})
    return out


def bpr_loss(input, label, name=None):
    """(reference: layers/nn.py bpr_loss)"""
    helper = LayerHelper("bpr_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="bpr_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Y": [out]})
    return out


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """(reference: layers/nn.py teacher_student_sigmoid_loss)"""
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="teacher_student_sigmoid_loss",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_max_up_bound": soft_max_up_bound,
               "soft_max_lower_bound": soft_max_lower_bound})
    return out


def dice_loss(input, label, epsilon=1e-5):
    """(reference: layers/nn.py dice_loss)"""
    helper = LayerHelper("dice_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="dice_loss_op",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Out": [out]},
                     attrs={"epsilon": epsilon})
    return out


def mean_iou(input, label, num_classes):
    """(reference: layers/nn.py mean_iou)"""
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int64")
    correct = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="mean_iou",
                     inputs={"Predictions": [input], "Labels": [label]},
                     outputs={"OutMeanIou": [miou], "OutWrong": [wrong],
                              "OutCorrect": [correct]},
                     attrs={"num_classes": num_classes})
    return miou, wrong, correct


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):
    """(reference: layers/nn.py sampling_id)"""
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="sampling_id", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"seed": seed})
    return out


def random_crop(x, shape, seed=None):
    """(reference: layers/nn.py random_crop)"""
    helper = LayerHelper("random_crop")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="random_crop", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"shape": list(shape)})
    return out


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """(reference: layers/nn.py add_position_encoding)"""
    helper = LayerHelper("add_position_encoding", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="add_position_encoding",
                     inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"alpha": alpha, "beta": beta})
    return out


def hash(input, hash_size, num_hash=1, name=None):
    """(reference: layers/nn.py hash; see ops/misc_ops.py for the hash
    function divergence note)"""
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="hash", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"num_hash": num_hash, "mod_by": hash_size})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """(reference: layers/nn.py row_conv)"""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    d = input.shape[-1]
    filt = helper.create_parameter(
        attr=param_attr, shape=[future_context_size + 1, d],
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="row_conv",
                     inputs={"X": [input], "Filter": [filt]},
                     outputs={"Out": [out]})
    return helper.append_activation(out)


def grid_sampler(x, grid, name=None):
    """(reference: layers/nn.py grid_sampler)"""
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="grid_sampler",
                     inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]})
    return out


def affine_grid(theta, out_shape, name=None):
    """(reference: layers/nn.py affine_grid)"""
    helper = LayerHelper("affine_grid", name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    inputs = {"Theta": [theta]}
    attrs = {}
    if hasattr(out_shape, "name"):
        inputs["OutputShape"] = [out_shape]
    else:
        attrs["output_shape"] = list(out_shape)
    helper.append_op(type="affine_grid", inputs=inputs,
                     outputs={"Output": [out]}, attrs=attrs)
    return out


def ctc_greedy_decoder(input, blank, name=None):
    """(reference: layers/nn.py ctc_greedy_decoder). Static-shape form:
    returns (decoded [B, T] padded with -1, lengths [B])."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    out = helper.create_variable_for_type_inference("int64")
    out_len = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="ctc_greedy_decoder",
                     inputs={"Input": [input]},
                     outputs={"Out": [out], "OutLength": [out_len]},
                     attrs={"blank": blank})
    return out, out_len


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """(reference: layers/nn.py lstm_unit) — fc of [x, h] then one cell
    step."""
    helper = LayerHelper("lstm_unit", name=name)
    hsz = hidden_t_prev.shape[1]
    gates = fc(input=[x_t, hidden_t_prev], size=4 * hsz,
               param_attr=param_attr, bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(type="lstm_unit",
                     inputs={"X": [gates], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [h]},
                     attrs={"forget_bias": forget_bias})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """(reference: layers/nn.py gru_unit); size = 3*hidden_dim."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    hsz = size // 3
    w = helper.create_parameter(attr=param_attr, shape=[hsz, 3 * hsz],
                                dtype=input.dtype)
    inputs = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if bias_attr is not False:
        from paddle_tpu.param_attr import ParamAttr

        bias = helper.create_parameter(
            attr=bias_attr if bias_attr not in (None, True) else ParamAttr(),
            shape=[1, 3 * hsz], dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [bias]
    h = helper.create_variable_for_type_inference(input.dtype)
    r = helper.create_variable_for_type_inference(input.dtype)
    g = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="gru_unit", inputs=inputs,
                     outputs={"Hidden": [h], "ResetHiddenPrev": [r],
                              "Gate": [g]})
    return h, r, g


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    """(reference: layers/ops.py gaussian_random)"""
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    from paddle_tpu.core.types import convert_np_dtype_to_dtype_

    helper.append_op(type="gaussian_random", outputs={"Out": [out]},
                     attrs={"shape": list(shape), "mean": mean,
                            "std": std, "seed": seed,
                            "dtype": int(convert_np_dtype_to_dtype_(dtype))})
    out.stop_gradient = True
    return out


def selu(x, scale=None, alpha=None, name=None):
    """(reference: layers/nn.py selu)"""
    helper = LayerHelper("selu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type="selu", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"scale": scale if scale is not None
               else 1.0507009873554805,
               "alpha": alpha if alpha is not None
               else 1.6732632423543772})
    return out


def has_inf(x):
    """(reference: layers/ops.py has_inf)"""
    helper = LayerHelper("has_inf")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="isinf", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def has_nan(x):
    """(reference: layers/ops.py has_nan)"""
    helper = LayerHelper("has_nan")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="isnan", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def isfinite(x):
    """(reference: layers/ops.py isfinite)"""
    helper = LayerHelper("isfinite")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op(type="isfinite_reduce", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def is_empty(x, cond=None):
    """(reference: layers/control_flow.py is_empty)"""
    helper = LayerHelper("is_empty")
    out = cond or helper.create_variable_for_type_inference("bool")
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None):
    """(reference: layers/nn.py conv3d), NCDHW."""
    helper = LayerHelper("conv3d", name=name, act=act, bias_attr=bias_attr)
    dtype = input.dtype
    channels = input.shape[1]
    to3 = lambda v: [v, v, v] if isinstance(v, int) else list(v)
    filter_size, stride = to3(filter_size), to3(stride)
    padding, dilation = to3(padding), to3(dilation)
    w = helper.create_parameter(
        attr=param_attr,
        shape=[num_filters, channels // groups] + filter_size, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d", inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": stride, "paddings": padding,
               "dilations": dilation, "groups": groups})
    pre_act = _conv_bias(helper, pre_bias)
    return helper.append_activation(pre_act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """(reference: layers/nn.py conv3d_transpose)"""
    helper = LayerHelper("conv3d_transpose", name=name, act=act,
                         bias_attr=bias_attr)
    dtype = input.dtype
    channels = input.shape[1]
    to3 = lambda v: [v, v, v] if isinstance(v, int) else list(v)
    filter_size, stride = to3(filter_size), to3(stride)
    padding = to3(padding)
    w = helper.create_parameter(
        attr=param_attr,
        shape=[channels, num_filters // groups] + filter_size, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="conv3d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={"strides": stride, "paddings": padding, "groups": groups})
    pre_act = _conv_bias(helper, pre_bias)
    return helper.append_activation(pre_act)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True):
    """(reference: layers/nn.py pool3d)"""
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    to3 = lambda v: [v, v, v] if isinstance(v, int) else list(v)
    helper.append_op(
        type="pool3d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"ksize": to3(pool_size), "strides": to3(pool_stride),
               "paddings": to3(pool_padding), "pooling_type": pool_type,
               "global_pooling": global_pooling,
               "exclusive": exclusive})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """(reference: layers/nn.py adaptive_pool2d) — output size fixed,
    kernel derived (requires divisible spatial dims for exact tiling)."""
    h, w = input.shape[2], input.shape[3]
    oh, ow = (pool_size, pool_size) if isinstance(pool_size, int) \
        else pool_size
    if h % oh or w % ow:
        raise ValueError(
            "adaptive_pool2d needs output size dividing the input "
            "spatial dims (%dx%d -> %dx%d)" % (h, w, oh, ow))
    return pool2d(input, pool_size=[h // oh, w // ow], pool_type=pool_type,
                  pool_stride=[h // oh, w // ow], name=name)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """(reference: layers/nn.py image_resize_short)"""
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    out_shape = [int(h * out_short_len / short),
                 int(w * out_short_len / short)]
    return image_resize(input, out_shape=out_shape, resample=resample)


def linear_chain_crf(input, label, param_attr=None, length=None):
    """(reference: layers/nn.py linear_chain_crf). Padded [B, T, C]
    emissions + optional lengths; returns per-sequence log-likelihood."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    num_tags = input.shape[-1]
    trans = helper.create_parameter(
        attr=param_attr, shape=[num_tags + 2, num_tags], dtype=input.dtype)
    ll = helper.create_variable_for_type_inference(input.dtype)
    alpha = helper.create_variable_for_type_inference(input.dtype)
    eexp = helper.create_variable_for_type_inference(input.dtype)
    texp = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Emission": [input], "Transition": [trans],
              "Label": [label]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="linear_chain_crf", inputs=inputs,
        outputs={"LogLikelihood": [ll], "Alpha": [alpha],
                 "EmissionExps": [eexp], "TransitionExps": [texp]})
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """(reference: layers/nn.py crf_decoding)"""
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    # the transition parameter is shared with linear_chain_crf by name
    trans = helper.main_program.global_block().var(param_attr.name)
    out = helper.create_variable_for_type_inference("int64")
    inputs = {"Emission": [input], "Transition": [trans]}
    if label is not None:
        inputs["Label"] = [label]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [out]})
    return out


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """(reference: layers/nn.py nce) with a uniform sampler."""
    helper = LayerHelper("nce", name=name, bias_attr=bias_attr)
    dim = input.shape[1]
    w = helper.create_parameter(
        attr=param_attr, shape=[num_total_classes, dim],
        dtype=input.dtype)
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=bias_attr if bias_attr not in (None, True) else ParamAttr(),
            shape=[num_total_classes, 1], dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    cost = helper.create_variable_for_type_inference(input.dtype)
    sl = helper.create_variable_for_type_inference(input.dtype)
    slab = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        type="nce", inputs=inputs,
        outputs={"Cost": [cost], "SampleLogits": [sl],
                 "SampleLabels": [slab]},
        attrs={"num_total_classes": num_total_classes,
               "num_neg_samples": num_neg_samples, "seed": seed})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None,
             is_custom=False, is_sparse=False):
    """(reference: layers/nn.py hsigmoid) over the default complete
    binary tree (custom paths unsupported)."""
    if is_custom or path_table is not None:
        raise NotImplementedError(
            "hsigmoid custom trees are not supported; the default "
            "complete binary tree matches the reference default")
    helper = LayerHelper("hsigmoid", name=name, bias_attr=bias_attr)
    dim = input.shape[1]
    w = helper.create_parameter(
        attr=param_attr, shape=[num_classes - 1, dim], dtype=input.dtype)
    inputs = {"X": [input], "Label": [label], "W": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=bias_attr if bias_attr not in (None, True) else ParamAttr(),
            shape=[num_classes - 1, 1], dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="hierarchical_sigmoid", inputs=inputs,
        outputs={"Out": [out], "PreOut": [pre]},
        attrs={"num_classes": num_classes})
    return out


def sequence_reshape(input, new_dim):
    """(reference: layers/nn.py sequence_reshape)"""
    helper = LayerHelper("sequence_reshape")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="sequence_reshape", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"new_dim": new_dim})
    return out


def sequence_scatter(input, index, updates, name=None):
    """(reference: layers/nn.py sequence_scatter)"""
    helper = LayerHelper("sequence_scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="sequence_scatter",
                     inputs={"X": [input], "Ids": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]})
    return out


def lod_reset(x, y=None, target_lod=None):
    """(reference: layers/nn.py lod_reset). In the padded+length world the
    data tensor is unchanged; lengths travel as separate tensors, so this
    is the identity on x (the new lengths are whatever Length tensor the
    caller threads onward)."""
    return x


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False, use_mkldnn=False):
    """(reference: layers/nn.py data_norm) — normalization by accumulated
    batch statistics held as persistable state."""
    helper = LayerHelper("data_norm", name=name, act=act)
    d = input.shape[-1]
    from paddle_tpu.initializer import ConstantInitializer

    bsize = helper.create_parameter(
        attr=ParamAttr(name=name and name + ".batch_size",
                       initializer=ConstantInitializer(1e4)),
        shape=[d], dtype=input.dtype)
    bsum = helper.create_parameter(
        attr=ParamAttr(name=name and name + ".batch_sum",
                       initializer=ConstantInitializer(0.0)),
        shape=[d], dtype=input.dtype)
    bsq = helper.create_parameter(
        attr=ParamAttr(name=name and name + ".batch_square_sum",
                       initializer=ConstantInitializer(1e4)),
        shape=[d], dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    means = helper.create_variable_for_type_inference(input.dtype)
    scales = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type="data_norm",
        inputs={"X": [input], "BatchSize": [bsize], "BatchSum": [bsum],
                "BatchSquareSum": [bsq]},
        outputs={"Y": [out], "Means": [means], "Scales": [scales]})
    return helper.append_activation(out)


def pow(x, factor=1.0, name=None):
    """(reference: layers/ops.py pow)"""
    helper = LayerHelper("pow", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="pow", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"factor": factor})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    """(reference: layers/ops.py uniform_random_batch_size_like)"""
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    from paddle_tpu.core.types import convert_np_dtype_to_dtype_

    helper.append_op(
        type="uniform_random_batch_size_like",
        inputs={"Input": [input]}, outputs={"Out": [out]},
        attrs={"shape": list(shape), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx, "min": min, "max": max,
               "seed": seed,
               "dtype": int(convert_np_dtype_to_dtype_(dtype))})
    out.stop_gradient = True
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    """(reference: layers/ops.py gaussian_random_batch_size_like)"""
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    from paddle_tpu.core.types import convert_np_dtype_to_dtype_

    helper.append_op(
        type="gaussian_random_batch_size_like",
        inputs={"Input": [input]}, outputs={"Out": [out]},
        attrs={"shape": list(shape), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx, "mean": mean, "std": std,
               "seed": seed,
               "dtype": int(convert_np_dtype_to_dtype_(dtype))})
    out.stop_gradient = True
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """(reference: layers/nn.py autoincreased_step_counter) — persistable
    int64 counter incremented once per executed step."""
    helper = LayerHelper("global_step_counter")
    counter = helper.block.program.global_block().create_var(
        name=counter_name or "@STEP_COUNTER@",
        dtype="int64", shape=[1], persistable=True)
    helper.block.program.global_block().vars[counter.name].desc.attrs[
        "init_value"] = float(begin - step)
    helper.append_op(
        type="increment", inputs={"X": [counter.name]},
        outputs={"Out": [counter.name]}, attrs={"step": float(step)})
    counter.stop_gradient = True
    return counter


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """(reference: layers/tensor.py create_parameter)"""
    helper = LayerHelper("create_parameter")
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias=is_bias,
                                   default_initializer=default_initializer)


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    """(reference: layers/nn.py im2sequence; op in ops/sequence_ops.py)"""
    helper = LayerHelper("im2sequence", name=name)
    to2 = lambda v: [v, v] if isinstance(v, int) else list(v)
    fs, st = to2(filter_size), to2(stride)
    pd = padding if isinstance(padding, (list, tuple)) and len(padding) == 4 \
        else to2(padding) * 2
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="im2sequence", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"kernels": fs, "strides": st,
                            "paddings": list(pd)})
    return out


def Print(input, first_n=-1, message=None, summarize=-1, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_lod=True, print_phase="both"):
    """(reference: layers/control_flow.py Print) — host-side debug print
    via jax.debug.print; the value passes through unchanged."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="print_op", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"message": message or input.name})
    return out


def tensor_array_to_tensor(input, axis=1, name=None):
    """(reference: layers/tensor.py tensor_array_to_tensor) — stack the
    live prefix of a tensor array."""
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference("float32")
    out_idx = helper.create_variable_for_type_inference("int64")
    helper.append_op(type="tensor_array_to_tensor",
                     inputs={"X": [input]},
                     outputs={"Out": [out], "OutIndex": [out_idx]},
                     attrs={"axis": axis})
    return out, out_idx


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    """(reference: layers/nn.py adaptive_pool3d)"""
    d, h, w = input.shape[2], input.shape[3], input.shape[4]
    od, oh, ow = (pool_size,) * 3 if isinstance(pool_size, int) \
        else pool_size
    if d % od or h % oh or w % ow:
        raise ValueError("adaptive_pool3d needs divisible spatial dims")
    k = [d // od, h // oh, w // ow]
    return pool3d(input, pool_size=k, pool_type=pool_type, pool_stride=k,
                  name=name)


def merge_selected_rows(x, name=None):
    """(reference: layers/nn.py merge_selected_rows). Gradients here are
    SelectedRows pytree values merged inside the optimizer lowerings, so
    at the layer level this is the identity."""
    return x


def get_tensor_from_selected_rows(x, name=None):
    """(reference: layers/nn.py get_tensor_from_selected_rows) — dense
    view; variables fetched across the jit boundary are already
    densified (engine/lowering.py)."""
    return x


def dynamic_lstmp(input, size, proj_size, h_0=None, c_0=None, seq_len=None,
                  param_attr=None, bias_attr=None, use_peepholes=False,
                  is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="tanh", dtype="float32", name=None):
    """LSTM with a recurrent projection (reference: layers/nn.py
    dynamic_lstmp → lstmp_op.cc): hidden H projected to P before the
    recurrence. Built as dynamic_lstm + a learned projection applied to
    the hidden sequence (the projected state feeds forward, matching the
    reference's output contract; the recurrent path uses H)."""
    hidden, cell = dynamic_lstm(
        input, size, h_0=h_0, c_0=c_0, seq_len=seq_len,
        param_attr=param_attr, bias_attr=bias_attr,
        use_peepholes=use_peepholes, is_reverse=is_reverse,
        gate_activation=gate_activation, cell_activation=cell_activation,
        candidate_activation=candidate_activation, dtype=dtype, name=name)
    proj = fc(input=hidden, size=proj_size, num_flatten_dims=2,
              bias_attr=False, act=proj_activation)
    return proj, cell


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Multi-layer (optionally bidirectional) LSTM (reference:
    layers/nn.py lstm → cudnn_lstm_op; here stacked dynamic_lstm scans).
    Returns (output, last_h, last_c) like the reference."""
    x = input
    for layer in range(num_layers):
        fw_in = fc(input=x, size=4 * hidden_size, num_flatten_dims=2,
                   bias_attr=False)
        # initial states apply to the first layer (the reference threads
        # per-layer init states; one shared pair covers the common case)
        h0 = init_h if layer == 0 else None
        c0 = init_c if layer == 0 else None
        fw, fc_state = dynamic_lstm(fw_in, 4 * hidden_size, h_0=h0,
                                    c_0=c0)
        if is_bidirec:
            bw_in = fc(input=x, size=4 * hidden_size, num_flatten_dims=2,
                       bias_attr=False)
            bw, _ = dynamic_lstm(bw_in, 4 * hidden_size, is_reverse=True)
            x = _concat_last(fw, bw)
        else:
            x = fw
        if dropout_prob and not is_test:
            x = dropout(x, dropout_prob)
    last_h = sequence_last_step(x)
    last_c = sequence_last_step(fc_state)
    return x, last_h, last_c


def _concat_last(a, b):
    helper = LayerHelper("concat")
    out = helper.create_variable_for_type_inference(a.dtype)
    helper.append_op(type="concat", inputs={"X": [a, b]},
                     outputs={"Out": [out]}, attrs={"axis": 2})
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_batch_idx=None, name=None):
    """Position-sensitive RoI pooling (reference: layers/nn.py psroi_pool
    → psroi_pool_op.cc)."""
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_idx is not None:
        inputs["RoisBatchIdx"] = [rois_batch_idx]
    helper.append_op(
        type="psroi_pool", inputs=inputs, outputs={"Out": [out]},
        attrs={"output_channels": output_channels,
               "spatial_scale": spatial_scale,
               "pooled_height": pooled_height,
               "pooled_width": pooled_width})
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """(reference: layers/nn.py chunk_eval). Returns (precision, recall,
    f1, num_infer_chunks, num_label_chunks, num_correct_chunks)."""
    helper = LayerHelper("chunk_eval")
    precision = helper.create_variable_for_type_inference("float32")
    recall = helper.create_variable_for_type_inference("float32")
    f1 = helper.create_variable_for_type_inference("float32")
    n_inf = helper.create_variable_for_type_inference("int64")
    n_lab = helper.create_variable_for_type_inference("int64")
    n_cor = helper.create_variable_for_type_inference("int64")
    inputs = {"Inference": [input], "Label": [label]}
    if seq_length is not None:
        inputs["SeqLength"] = [seq_length]
    helper.append_op(
        type="chunk_eval", inputs=inputs,
        outputs={"Precision": [precision], "Recall": [recall],
                 "F1-Score": [f1], "NumInferChunks": [n_inf],
                 "NumLabelChunks": [n_lab], "NumCorrectChunks": [n_cor]},
        attrs={"chunk_scheme": chunk_scheme,
               "num_chunk_types": num_chunk_types,
               "excluded_chunk_types": excluded_chunk_types or []})
    return precision, recall, f1, n_inf, n_lab, n_cor


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """Run a python callable inside the graph (reference: layers/nn.py
    py_func → py_func_op.cc, here via jax.pure_callback — see
    ops/misc_ops.py). ``out`` vars need static shapes; with
    ``backward_func(x..., dout...) -> dx...`` the op is differentiable.

    CONVENTION DIVERGENCE from the reference: backward_func receives the
    forward INPUTS followed by the output grads (NOT the forward outputs
    — recompute them inside if needed), and skip_vars_in_backward_input
    is not supported."""
    from paddle_tpu.ops.misc_ops import register_py_func

    if skip_vars_in_backward_input is not None:
        raise NotImplementedError(
            "py_func: skip_vars_in_backward_input is not supported — "
            "backward_func receives (inputs..., out_grads...) here")
    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    from paddle_tpu.core.types import convert_dtype_to_np

    attrs = {
        "func_id": register_py_func(func),
        "out_shapes": [list(o.shape) for o in outs],
        "out_dtypes": [str(convert_dtype_to_np(o.dtype)) for o in outs],
    }
    if backward_func is not None:
        attrs["backward_func_id"] = register_py_func(backward_func)
    helper.append_op(type="py_func", inputs={"X": list(xs)},
                     outputs={"Out": list(outs)}, attrs=attrs)
    if backward_func is None:
        for o in outs:
            o.stop_gradient = True
    return out


def load(out, file_path, load_as_fp16=None):
    """(reference: layers/io.py load → load_op loading a saved var file
    at run time). Here the file is read eagerly at build time (reference
    tensor-stream or .npy) and assigned as the var's init value via an
    assign op on first run."""
    from paddle_tpu.ops.misc_ops import (_load_from_file,
                                         register_load_value)

    # eager read (errors surface at build time); the op re-reads by path
    # after deserialization in a fresh process
    arr = _load_from_file(file_path, bool(load_as_fp16))
    register_load_value(arr, file_path, bool(load_as_fp16))
    helper = LayerHelper("load")
    helper.append_op(
        type="load_value", inputs={},
        outputs={"Out": [out]},
        attrs={"file_path": file_path,
               "load_as_fp16": bool(load_as_fp16)})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """(reference: layers/control_flow.py reorder_lod_tensor_by_rank).
    The padded+length representation never reorders rows by length —
    masked scans make reordering unnecessary (see DynamicRNN) — so this
    is the identity."""
    return x


def similarity_focus(input, axis, indexes, name=None):
    """(reference: layers/nn.py similarity_focus)"""
    helper = LayerHelper("similarity_focus", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type="similarity_focus", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"axis": axis, "indexes": list(indexes)})
    return out


def fused_attention(q, k, v, causal=False, scale=None, seq_lens=None,
                    dropout_rate=0.0, name=None, sequence_parallel=False,
                    sp_axis="sp", sp_batch_axis=None):
    """Whole-attention fusion over [B, H, T, D] inputs: the Pallas
    flash-attention kernel on TPU, plain-XLA composition elsewhere.

    Beyond-reference TPU-first layer (the reference composes
    matmul+softmax+dropout; its fused-op strategy lives in
    paddle/fluid/operators/fused/). ``seq_lens`` ([B] or [B, 1] int)
    replaces the reference's additive [B, H, T, T] padding masks with
    per-sequence valid lengths; ``causal`` is a static flag;
    ``dropout_rate`` is attention-weight dropout executed inside the
    kernel. Not part of the fluid.layers golden surface (kept out of
    __all__); models reach it via this module directly.
    """
    helper = LayerHelper("fused_attention", name=name)
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    outputs = {"Out": [out]}
    if seq_lens is not None:
        inputs["SeqLens"] = [seq_lens]
    attrs = {"causal": bool(causal), "dropout_rate": float(dropout_rate)}
    if sequence_parallel:
        # ring attention over the mesh's sequence-parallel axis
        # (parallel/ring_attention.py) — requires T divisible by the
        # sp axis size and no dropout/seq_lens
        attrs["sequence_parallel"] = True
        attrs["sp_axis"] = sp_axis
        if sp_batch_axis:
            attrs["sp_batch_axis"] = sp_batch_axis
    else:
        # softmax residual (per-row logsumexp): saved so the registered
        # fused_attention_grad can run the flash backward kernels from
        # (Out, Lse) without re-executing the forward custom call
        outputs["Lse"] = [
            helper.create_variable_for_type_inference(dtype="float32")]
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(type="fused_attention", inputs=inputs,
                     outputs=outputs, attrs=attrs)
    return out


# Additive mask magnitude: large enough that softmax zeroes the masked
# keys in every float dtype, small enough not to overflow float16.
_ATTN_MASK_BIG = 1e9


def attention_bias_from_lens(seq_lens, max_len, name=None):
    """Additive key-padding attention bias [B, 1, 1, max_len] from a
    per-sequence lengths vector: 0 for valid keys, -1e9 past each
    sequence's length. The canonical mask emission for the UNFUSED
    attention composition — built from exactly the ops
    (sequence_mask → scale → reshape2) the analysis fuse-attention
    transform pass recognizes, so the lengths vector round-trips into
    the fused op's ``SeqLens`` input when the rewrite fires. Every
    intermediate is stop_gradient: the mask is data, not model."""
    mask = sequence_mask(seq_lens, maxlen=int(max_len))  # [B, T] of 0/1
    mask.stop_gradient = True
    bias = scale(mask, scale=_ATTN_MASK_BIG, bias=-_ATTN_MASK_BIG,
                 name=name)  # 1 -> 0, 0 -> -BIG
    bias.stop_gradient = True
    bias = reshape(bias, shape=[-1, 1, 1, int(max_len)])
    bias.stop_gradient = True
    return bias


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """Tree-based convolution on a per-sample tree structure (reference:
    layers/nn.py:10276 tree_conv + operators/tree_conv_op.cc).
    nodes_vector [B, N, F]; edge_set [B, E, 2] 1-based directed edges;
    returns [B, N, output_size, num_filters]."""
    helper = LayerHelper("tree_conv", **locals())
    dtype = nodes_vector.dtype
    feature_size = nodes_vector.shape[2]
    w = helper.create_parameter(
        attr=param_attr, shape=[feature_size, 3, output_size, num_filters],
        dtype=dtype, is_bias=False)
    if name is None:
        out = helper.create_variable_for_type_inference(dtype=dtype)
    else:
        out = helper.create_variable(name=name, dtype=dtype)
    helper.append_op(
        type="tree_conv",
        inputs={"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                "Filter": [w]},
        outputs={"Out": [out]},
        attrs={"max_depth": max_depth})
    if bias_attr:
        pre_activation = helper.append_bias_op(out, dim_start=2)
    else:
        pre_activation = out
    return helper.append_activation(pre_activation)
