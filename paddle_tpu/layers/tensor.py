"""Tensor-building layers (reference: python/paddle/fluid/layers/tensor.py)."""

import numpy as np

from paddle_tpu.framework import Variable, default_main_program
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.core.types import convert_np_dtype_to_dtype_

__all__ = [
    "create_tensor",
    "create_global_var",
    "fill_constant",
    "fill_constant_batch_size_like",
    "cast",
    "concat",
    "sums",
    "assign",
    "zeros",
    "ones",
    "reverse",
    "argmax",
    "argmin",
    "argsort",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(
        name=helper.name, dtype=dtype, persistable=persistable
    )


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        persistable=persistable, name=helper.name, shape=shape, dtype=dtype
    )
    from paddle_tpu.initializer import ConstantInitializer

    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def fill_constant(shape, dtype, value, force_cpu=False, out=None, block=None):
    helper = LayerHelper("fill_constant", block=block)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": int(convert_np_dtype_to_dtype_(dtype)),
            "value": float(value),
        },
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": int(convert_np_dtype_to_dtype_(dtype)),
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    out.stop_gradient = True
    return out


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="cast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={
            "in_dtype": int(x.dtype),
            "out_dtype": int(convert_np_dtype_to_dtype_(dtype)),
        },
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op(
        type="concat",
        inputs={"X": input},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op(type="sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=input.dtype)
        helper.append_op(
            type="assign", inputs={"X": [input]}, outputs={"Out": [output]}
        )
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=arr.dtype.name)
        key = "fp32_values" if arr.dtype.kind == "f" else "int32_values"
        helper.append_op(
            type="assign_value",
            outputs={"Out": [output]},
            attrs={
                "shape": list(arr.shape),
                "dtype": int(convert_np_dtype_to_dtype_(arr.dtype)),
                key: [float(v) if arr.dtype.kind == "f" else int(v)
                      for v in arr.flatten()],
            },
        )
    return output


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    if isinstance(axis, int):
        axis = [axis]
    helper.append_op(
        type="reverse",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="arg_max",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="arg_min",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    ids = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="argsort",
        inputs={"X": [input]},
        outputs={"Out": [out], "Indices": [ids]},
        attrs={"axis": axis},
    )
    return out, ids
