"""Loss layers (reference: python/paddle/fluid/layers/nn.py cross_entropy,
softmax_with_cross_entropy:5659, square_error_cost, ...)."""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "cross_entropy",
    "softmax_with_cross_entropy",
    "square_error_cost",
    "sigmoid_cross_entropy_with_logits",
    "log_loss",
    "huber_loss",
    "smooth_l1",
    "kldiv_loss",
    "hinge_loss",
    "warpctc",
    "edit_distance",
]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(dtype=logits.dtype)
    loss = helper.create_variable_for_type_inference(dtype=logits.dtype)
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={
            "soft_label": soft_label,
            "ignore_index": ignore_index,
            "numeric_stable_mode": numeric_stable_mode,
        },
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="square_error_cost",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out]},
    )
    return out


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper("log_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="log_loss",
        inputs={"Predicted": [input], "Labels": [label]},
        outputs={"Loss": [out]},
        attrs={"epsilon": epsilon},
    )
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    residual = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                         stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="huber_loss",
        inputs={"X": [input], "Y": [label]},
        outputs={"Residual": [residual], "Out": [out]},
        attrs={"delta": float(delta)},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    diff = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                     stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="smooth_l1_loss",
        inputs={"X": [x], "Y": [y]},
        outputs={"Diff": [diff], "Out": [out]},
        attrs={"sigma": sigma if sigma is not None else 1.0},
    )
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="kldiv_loss",
        inputs={"X": [x], "Target": [target]},
        outputs={"Loss": [out]},
        attrs={"reduction": reduction},
    )
    return out


def hinge_loss(input, label, name=None):
    helper = LayerHelper("hinge_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="hinge_loss",
        inputs={"Logits": [input], "Labels": [label]},
        outputs={"Loss": [out]},
    )
    return out


def warpctc(input, label, blank=0, norm_by_times=False, use_cudnn=False,
            input_length=None, label_length=None):
    """CTC loss (reference: layers/nn.py warpctc → warpctc_op.cc).
    ``input``: [B, T, C] unnormalized logits (batch-major padded form of
    the reference's LoD logits); returns [B, 1] per-sequence loss."""
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        inputs["LogitsLength"] = [input_length]
    if label_length is not None:
        inputs["LabelLength"] = [label_length]
    helper.append_op(
        type="warpctc", inputs=inputs, outputs={"Loss": [loss]},
        attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance (reference: layers/nn.py edit_distance).
    Returns (distance [B, 1], sequence_num [1])."""
    helper = LayerHelper("edit_distance", name=name)
    out = helper.create_variable_for_type_inference(dtype="float32")
    seq_num = helper.create_variable_for_type_inference(dtype="int64")
    inputs = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        inputs["HypsLength"] = [input_length]
    if label_length is not None:
        inputs["RefsLength"] = [label_length]
    helper.append_op(
        type="edit_distance", inputs=inputs,
        outputs={"Out": [out], "SequenceNum": [seq_num]},
        attrs={"normalized": normalized,
               "ignored_tokens": list(ignored_tokens or [])})
    return out, seq_num
