"""Detection layers (reference: python/paddle/fluid/layers/detection.py).

Static-shape conventions of the op layer apply: NMS and matching return
fixed-capacity tensors with -1 padding; RoI ops take an explicit
per-roi batch index instead of LoD (see ops/detection_ops.py).
"""

from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu import unique_name

__all__ = [
    "prior_box",
    "density_prior_box",
    "anchor_generator",
    "box_coder",
    "iou_similarity",
    "box_clip",
    "polygon_box_transform",
    "bipartite_match",
    "target_assign",
    "multiclass_nms",
    "roi_align",
    "roi_pool",
    "detection_output",
    "ssd_loss",
    "multi_box_head",
    "yolov3_loss",
    "detection_map",
    "generate_proposals",
    "rpn_target_assign",
    "generate_proposal_labels",
    "roi_perspective_transform",
    "generate_mask_labels",
]


def _out(helper, dtype="float32"):
    return helper.create_variable_for_type_inference(dtype=dtype)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    """(reference: layers/detection.py:1108)"""
    helper = LayerHelper("prior_box", name=name)
    boxes, var = _out(helper), _out(helper)
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
            "min_max_aspect_ratios_order": min_max_aspect_ratios_order,
        })
    return boxes, var


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    """(reference: layers/detection.py:1228)"""
    helper = LayerHelper("density_prior_box", name=name)
    boxes, var = _out(helper), _out(helper)
    helper.append_op(
        type="density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [var]},
        attrs={
            "densities": list(densities or []),
            "fixed_sizes": list(fixed_sizes or []),
            "fixed_ratios": list(fixed_ratios or [1.0]),
            "variances": list(variance),
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
            "flatten_to_2d": flatten_to_2d,
        })
    return boxes, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    """(reference: layers/detection.py:1600)"""
    helper = LayerHelper("anchor_generator", name=name)
    anchors, var = _out(helper), _out(helper)
    helper.append_op(
        type="anchor_generator",
        inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [var]},
        attrs={
            "anchor_sizes": list(anchor_sizes or [64.0, 128.0, 256.0]),
            "aspect_ratios": list(aspect_ratios or [0.5, 1.0, 2.0]),
            "variances": list(variance),
            "stride": list(stride or [16.0, 16.0]),
            "offset": offset,
        })
    return anchors, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    """(reference: layers/detection.py:345)"""
    helper = LayerHelper("box_coder", name=name)
    out = _out(helper)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_coder", inputs=inputs, outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized,
               "axis": axis})
    return out


def iou_similarity(x, y, box_normalized=True, name=None):
    """(reference: layers/detection.py:317)"""
    helper = LayerHelper("iou_similarity", name=name)
    out = _out(helper)
    helper.append_op(type="iou_similarity",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"box_normalized": box_normalized})
    return out


def box_clip(input, im_info, name=None):
    """(reference: layers/detection.py:2059)"""
    helper = LayerHelper("box_clip", name=name)
    out = _out(helper)
    helper.append_op(type="box_clip",
                     inputs={"Input": [input], "ImInfo": [im_info]},
                     outputs={"Output": [out]})
    return out


def polygon_box_transform(input, name=None):
    """(reference: layers/detection.py:482)"""
    helper = LayerHelper("polygon_box_transform", name=name)
    out = _out(helper)
    helper.append_op(type="polygon_box_transform",
                     inputs={"Input": [input]},
                     outputs={"Output": [out]})
    return out


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """(reference: layers/detection.py:702)"""
    helper = LayerHelper("bipartite_match", name=name)
    match_idx = _out(helper, "int32")
    match_dist = _out(helper)
    helper.append_op(
        type="bipartite_match",
        inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [match_idx],
                 "ColToRowMatchDist": [match_dist]},
        attrs={"match_type": match_type or "bipartite",
               "dist_threshold": dist_threshold or 0.5})
    return match_idx, match_dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """(reference: layers/detection.py:788)"""
    helper = LayerHelper("target_assign", name=name)
    out = _out(helper, input.dtype)
    out_weight = _out(helper)
    helper.append_op(
        type="target_assign",
        inputs={"X": [input], "MatchIndices": [matched_indices]},
        outputs={"Out": [out], "OutWeight": [out_weight]},
        attrs={"mismatch_value": mismatch_value})
    return out, out_weight


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    """(reference: layers/detection.py:2107). Static-shape output:
    [B, keep_top_k, 6] rows (label, score, x1, y1, x2, y2) padded with
    label -1, plus a [B] kept-count tensor."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = _out(helper)
    count = _out(helper, "int32")
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out], "NmsRoisNum": [count]},
        attrs={
            "score_threshold": score_threshold,
            "nms_top_k": nms_top_k,
            "keep_top_k": keep_top_k,
            "nms_threshold": nms_threshold,
            "normalized": normalized,
            "nms_eta": nms_eta,
            "background_label": background_label,
        })
    return out, count


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_batch_idx=None,
              name=None):
    """(reference: layers/roi_align; rois_batch_idx replaces the LoD)"""
    helper = LayerHelper("roi_align", name=name)
    out = _out(helper, input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_idx is not None:
        inputs["RoisBatchIdx"] = [rois_batch_idx]
    helper.append_op(
        type="roi_align", inputs=inputs, outputs={"Out": [out]},
        attrs={"pooled_height": pooled_height,
               "pooled_width": pooled_width,
               "spatial_scale": spatial_scale,
               "sampling_ratio": sampling_ratio})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_batch_idx=None, name=None):
    """(reference: layers/roi_pool)"""
    helper = LayerHelper("roi_pool", name=name)
    out = _out(helper, input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_idx is not None:
        inputs["RoisBatchIdx"] = [rois_batch_idx]
    helper.append_op(
        type="roi_pool", inputs=inputs, outputs={"Out": [out]},
        attrs={"pooled_height": pooled_height,
               "pooled_width": pooled_width,
               "spatial_scale": spatial_scale})
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     name=None):
    """Decode + NMS (reference: layers/detection.py:204 — box_coder
    decode_center_size followed by multiclass_nms)."""
    from paddle_tpu.layers import nn as nn_layers

    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores_t = nn_layers.transpose(scores, perm=[0, 2, 1])  # [B, C, M]
    out, count = multiclass_nms(
        decoded, scores_t, score_threshold=score_threshold,
        nms_top_k=nms_top_k, keep_top_k=keep_top_k,
        nms_threshold=nms_threshold, nms_eta=nms_eta,
        background_label=background_label, name=name)
    return out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mismatch_value=0, normalize=True, sample_size=None,
             mining_type="max_negative"):
    """SSD multibox loss (reference: layers/detection.py:874): match
    priors to ground truths (bipartite + per-prediction), smooth-L1 on
    matched locations, softmax CE with matched/background label targets.
    Hard negative mining is replaced by full negative weighting
    (TPU-friendly static shapes); sample_size/neg_pos_ratio are accepted
    for API parity. Single-image form: location [M, 4], confidence
    [M, C], gt_box [N_gt, 4], gt_label [N_gt, 1], prior_box [M, 4]."""
    from paddle_tpu.layers import loss as loss_layers
    from paddle_tpu.layers import nn as nn_layers

    if mining_type != "max_negative":
        # same guard as the reference (layers/detection.py ssd_loss:
        # "Only mining_type == max_negative is supported")
        raise ValueError("ssd_loss: only mining_type == 'max_negative' "
                         "is supported")
    iou = iou_similarity(gt_box, prior_box)            # [N_gt, M]
    match_idx, _ = bipartite_match(iou, match_type,
                                   overlap_threshold)  # [1, M]
    match_idx.stop_gradient = True
    # per-prior location target: enc[match[m], m] (zeros unmatched)
    enc = box_coder(prior_box, prior_box_var, gt_box)  # [N_gt, M, 4]
    loc_target, loc_w = _gather_encoded(enc, match_idx)   # [M, 4], [M, 1]
    loc_target.stop_gradient = True
    # conf target: gt label where matched, background elsewhere
    conf_target, _ = target_assign(
        gt_label, match_idx, mismatch_value=background_label)  # [1, M, 1]
    conf_target = nn_layers.reshape(conf_target, shape=[-1, 1])
    conf_target.stop_gradient = True

    loc_loss = nn_layers.reduce_sum(
        nn_layers.elementwise_mul(
            loss_layers.smooth_l1(location, loc_target), loc_w))
    conf_loss = nn_layers.reduce_sum(
        loss_layers.softmax_with_cross_entropy(
            logits=confidence, label=conf_target))
    total = nn_layers.elementwise_add(
        nn_layers.scale(loc_loss, scale=loc_loss_weight),
        nn_layers.scale(conf_loss, scale=conf_loss_weight))
    if normalize:
        denom = nn_layers.scale(nn_layers.reduce_sum(loc_w), scale=1.0,
                                bias=1e-6)
        total = nn_layers.elementwise_div(total, denom)
    return total


def _gather_encoded(enc, match_idx):
    """enc [N_gt, M, 4] -> per-prior target [M, 4] + matched weight
    [M, 1] via the match index (the gather the reference fuses into its
    ssd_loss Python composition)."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("gather_encoded")
    out = helper.create_variable_for_type_inference(dtype=enc.dtype)
    wt = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(
        type="gather_encoded",
        inputs={"Encoded": [enc], "MatchIndices": [match_idx]},
        outputs={"Out": [out], "OutWeight": [wt]})
    return out, wt


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (reference: layers/detection.py:1354): per
    feature map, generate priors and 3x3/1x1 conv loc+conf predictions,
    reshape and concat across maps. Returns
    (mbox_locs, mbox_confs, boxes, variances)."""
    from paddle_tpu.layers import nn as nn_layers
    from paddle_tpu.layers import tensor as tensor_layers

    n_maps = len(inputs)
    if min_sizes is None:
        # the reference's ratio interpolation
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n_maps - 2)) \
            if n_maps > 2 else 0
        for ratio in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes[:n_maps - 1]
        max_sizes = [base_size * 0.20] + max_sizes[:n_maps - 1]

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        ms_list = ms if isinstance(ms, (list, tuple)) else [ms]
        mx = max_sizes[i] if max_sizes else None
        mx_list = (mx if isinstance(mx, (list, tuple)) else [mx]) \
            if mx is not None else None
        ar = aspect_ratios[i]
        ar_list = ar if isinstance(ar, (list, tuple)) else [ar]
        st = steps[i] if steps else (
            (step_w[i] if step_w else 0.0, step_h[i] if step_h else 0.0))
        if not isinstance(st, (list, tuple)):
            st = (st, st)  # canonical SSD configs give one scalar per map
        box, var = prior_box(
            feat, image, min_sizes=ms_list, max_sizes=mx_list,
            aspect_ratios=ar_list, variance=variance, flip=flip,
            clip=clip, steps=list(st), offset=offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        from paddle_tpu.ops.detection_ops import _expand_aspect_ratios

        num_priors = (len(ms_list) * len(_expand_aspect_ratios(
            ar_list, flip)) + (len(mx_list) if mx_list else 0))
        loc = nn_layers.conv2d(feat, num_filters=num_priors * 4,
                               filter_size=kernel_size, padding=pad,
                               stride=stride)
        conf = nn_layers.conv2d(feat, num_filters=num_priors * num_classes,
                                filter_size=kernel_size, padding=pad,
                                stride=stride)
        # NCHW -> [B, H*W*priors, 4 / num_classes]
        loc = nn_layers.transpose(loc, perm=[0, 2, 3, 1])
        loc = nn_layers.reshape(loc, shape=[-1 if loc.shape[0] in (None, -1)
                                            else loc.shape[0],
                                            _numel(loc.shape[1:]) // 4, 4])
        conf = nn_layers.transpose(conf, perm=[0, 2, 3, 1])
        conf = nn_layers.reshape(
            conf, shape=[-1 if conf.shape[0] in (None, -1)
                         else conf.shape[0],
                         _numel(conf.shape[1:]) // num_classes,
                         num_classes])
        box = nn_layers.reshape(box, shape=[-1, 4])
        var = nn_layers.reshape(var, shape=[-1, 4])
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(box)
        vars_all.append(var)

    mbox_locs = tensor_layers.concat(locs, axis=1) if len(locs) > 1 else locs[0]
    mbox_confs = tensor_layers.concat(confs, axis=1) \
        if len(confs) > 1 else confs[0]
    boxes = tensor_layers.concat(boxes_all, axis=0) \
        if len(boxes_all) > 1 else boxes_all[0]
    variances = tensor_layers.concat(vars_all, axis=0) \
        if len(vars_all) > 1 else vars_all[0]
    return mbox_locs, mbox_confs, boxes, variances


def _numel(shape):
    n = 1
    for d in shape:
        n *= d
    return n


def yolov3_loss(x, gtbox, gtlabel, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, name=None):
    """(reference: layers/detection.py:508)"""
    helper = LayerHelper("yolov3_loss", name=name)
    loss = _out(helper)
    obj_mask = _out(helper)
    match_mask = _out(helper, "int32")
    helper.append_op(
        type="yolov3_loss",
        inputs={"X": [x], "GTBox": [gtbox], "GTLabel": [gtlabel]},
        outputs={"Loss": [loss], "ObjectnessMask": [obj_mask],
                 "GTMatchMask": [match_mask]},
        attrs={"anchors": list(anchors),
               "anchor_mask": list(anchor_mask),
               "class_num": class_num,
               "ignore_thresh": ignore_thresh,
               "downsample_ratio": downsample_ratio})
    return loss


def _np_map(dets, gts, overlap_threshold, ap_version,
            background_label=0, evaluate_difficult=True):
    """Host-side mAP (the computation of the reference's detection_map
    op, operators/detection/detection_map_op.h): greedy IoU matching per
    class, AP by 'integral' or '11point', background class excluded.
    dets: [B, K, 6] rows (label, score, x1, y1, x2, y2) padded label<0;
    gts: [B, G, 5] rows (label, x1, y1, x2, y2) — or [B, G, 6] with a
    trailing is_difficult flag honored when evaluate_difficult=False
    (difficult gts neither count as positives nor penalize matches)."""
    import numpy as np

    def iou(a, b):
        ix = min(a[2], b[2]) - max(a[0], b[0])
        iy = min(a[3], b[3]) - max(a[1], b[1])
        if ix <= 0 or iy <= 0:
            return 0.0
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / max(ua, 1e-10)

    has_difficult = gts.shape[-1] >= 6
    classes = sorted({int(g[0]) for img in gts for g in img
                      if g[0] >= 0 and int(g[0]) != background_label})
    aps = []
    for c in classes:
        records = []   # (score, is_tp)
        n_gt = 0
        for b in range(len(gts)):
            rows = [g for g in gts[b] if int(g[0]) == c]
            gt_c = [g[1:5] for g in rows]
            diff = [bool(g[5]) if has_difficult else False for g in rows]
            n_gt += sum(1 for d_ in diff if evaluate_difficult or not d_)
            used = [False] * len(gt_c)
            det_c = sorted([d for d in dets[b] if int(d[0]) == c],
                           key=lambda d: -d[1])
            for d in det_c:
                best, best_i = 0.0, -1
                for i, g in enumerate(gt_c):
                    o = iou(d[2:], g)
                    if o > best:
                        best, best_i = o, i
                if (best > overlap_threshold and best_i >= 0
                        and not evaluate_difficult and diff[best_i]):
                    continue  # difficult match: neither TP nor FP
                tp = best > overlap_threshold and not used[best_i]
                if tp:
                    used[best_i] = True
                records.append((float(d[1]), tp))
        if n_gt == 0:
            continue
        records.sort(key=lambda r: -r[0])
        tps = np.cumsum([1.0 if r[1] else 0.0 for r in records]) \
            if records else np.zeros(0)
        fps = np.cumsum([0.0 if r[1] else 1.0 for r in records]) \
            if records else np.zeros(0)
        recall = tps / n_gt if len(tps) else np.zeros(0)
        precision = tps / np.maximum(tps + fps, 1e-10) \
            if len(tps) else np.zeros(0)
        if ap_version == "11point":
            ap = 0.0
            for t in np.arange(0.0, 1.01, 0.1):
                p = precision[recall >= t].max() \
                    if np.any(recall >= t) else 0.0
                ap += p / 11.0
        else:  # integral
            ap, prev_r = 0.0, 0.0
            for p, r in zip(precision, recall):
                ap += p * (r - prev_r)
                prev_r = r
        aps.append(ap)
    return np.float32(np.mean(aps) if aps else 0.0)


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral"):
    """mAP metric (reference: layers/detection.py:610 → detection_map
    op). Runs host-side through py_func on the static-shape detection
    format; returns a [1] float map value."""
    from paddle_tpu.layer_helper import LayerHelper
    from paddle_tpu.layers import nn as nn_layers

    if input_states is not None or out_states is not None:
        raise NotImplementedError(
            "detection_map: streaming state accumulation "
            "(input_states/out_states) is not supported — compute mAP "
            "per evaluation pass or accumulate detections host-side "
            "(metrics.DetectionMAP does this)")
    del has_state
    helper = LayerHelper("detection_map")
    out = helper.create_variable_for_type_inference("float32")
    out.desc.shape = [1]

    def compute(dets, gts):
        import numpy as np

        return _np_map(np.asarray(dets), np.asarray(gts),
                       overlap_threshold, ap_version,
                       background_label=background_label,
                       evaluate_difficult=evaluate_difficult).reshape(1)

    nn_layers.py_func(compute, [detect_res, label], [out])
    return out


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None,
                       return_rois_num=False):
    """(reference: layers/detection.py:1972). Static-shape outputs:
    (rpn_rois [N, post, 4], rpn_roi_probs [N, post, 1]) zero-padded past
    each image's proposal count — pass return_rois_num=True to also get
    the [N] per-image count and mask the padding downstream. ``eta``
    (adaptive NMS) is accepted but unsupported under static shapes."""
    helper = LayerHelper("generate_proposals", name=name)
    rois = _out(helper)
    probs = _out(helper)
    count = _out(helper, "int32")
    helper.append_op(
        type="generate_proposals",
        inputs={"Scores": [scores], "BboxDeltas": [bbox_deltas],
                "ImInfo": [im_info], "Anchors": [anchors],
                "Variances": [variances]},
        outputs={"RpnRois": [rois], "RpnRoiProbs": [probs],
                 "RpnRoisNum": [count]},
        attrs={"pre_nms_topN": pre_nms_top_n,
               "post_nms_topN": post_nms_top_n,
               "nms_thresh": nms_thresh, "min_size": min_size,
               "eta": eta})
    if return_rois_num:
        return rois, probs, count
    return rois, probs


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """(reference: layers/detection.py:57). With bbox_pred/cls_logits
    given, returns the REFERENCE 5-tuple (score_pred [M, 1],
    loc_pred [M, 4], score_target [M, 1] in {1, 0, -1(ignore)},
    loc_target [M, 4], bbox_inside_weight [M, 1]) in dense per-anchor
    form — mask score terms where score_target < 0 and weight location
    terms by bbox_inside_weight, instead of the reference's gathered
    subsets. With preds omitted, returns the raw per-anchor targets
    (score_target, bbox_target, bbox_weight, loc_index, score_index)."""
    helper = LayerHelper("rpn_target_assign")
    score_t = _out(helper, "int32")
    bbox_t = _out(helper)
    bbox_w = _out(helper)
    loc_i = _out(helper, "int64")
    score_i = _out(helper, "int64")
    inputs = {"Anchor": [anchor_box], "GtBoxes": [gt_boxes]}
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd]
    if im_info is not None:
        inputs["ImInfo"] = [im_info]
    helper.append_op(
        type="rpn_target_assign", inputs=inputs,
        outputs={"ScoreTarget": [score_t], "BboxTarget": [bbox_t],
                 "BboxWeight": [bbox_w], "LocationIndex": [loc_i],
                 "ScoreIndex": [score_i]},
        attrs={"rpn_batch_size_per_im": rpn_batch_size_per_im,
               "rpn_fg_fraction": rpn_fg_fraction,
               "rpn_positive_overlap": rpn_positive_overlap,
               "rpn_negative_overlap": rpn_negative_overlap,
               "rpn_straddle_thresh": rpn_straddle_thresh,
               "use_random": use_random})
    if bbox_pred is not None and cls_logits is not None:
        from paddle_tpu.layers import nn as nn_layers

        score_pred = nn_layers.reshape(cls_logits, shape=[-1, 1])
        loc_pred = nn_layers.reshape(bbox_pred, shape=[-1, 4])
        score_tgt = nn_layers.reshape(score_t, shape=[-1, 1])
        return score_pred, loc_pred, score_tgt, bbox_t, bbox_w
    return score_t, bbox_t, bbox_w, loc_i, score_i


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info=None, rpn_rois_num=None,
                             batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True):
    """(reference: layers/detection.py:1743). Static single-image form:
    returns (rois [P, 4], labels_int32 [P], bbox_targets
    [P, 4*class_nums], bbox_inside_weights, bbox_outside_weights) with
    P = batch_size_per_im; padding rows carry label -1, zero weights."""
    helper = LayerHelper("generate_proposal_labels")
    rois = _out(helper)
    labels = _out(helper, "int32")
    tgts = _out(helper)
    in_w = _out(helper)
    out_w = _out(helper)
    inputs = {"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
              "GtBoxes": [gt_boxes]}
    if is_crowd is not None:
        inputs["IsCrowd"] = [is_crowd]
    if im_info is not None:
        inputs["ImInfo"] = [im_info]
    if rpn_rois_num is not None:
        inputs["RpnRoisNum"] = [rpn_rois_num]
    helper.append_op(
        type="generate_proposal_labels", inputs=inputs,
        outputs={"Rois": [rois], "LabelsInt32": [labels],
                 "BboxTargets": [tgts], "BboxInsideWeights": [in_w],
                 "BboxOutsideWeights": [out_w]},
        attrs={"batch_size_per_im": batch_size_per_im,
               "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
               "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
               "bbox_reg_weights": list(bbox_reg_weights),
               "class_nums": class_nums or 81,
               "use_random": use_random})
    return rois, labels, tgts, in_w, out_w


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_batch_idx=None, name=None):
    """Warp quadrilateral RoIs ([R, 8] clockwise quads) to a fixed
    [transformed_height, transformed_width] grid (reference:
    layers/detection.py:1695 + detection/roi_perspective_transform_op.cc).
    ``rois_batch_idx`` replaces the reference's LoD."""
    helper = LayerHelper("roi_perspective_transform", name=name)
    out = _out(helper, input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_idx is not None:
        inputs["RoisBatchIdx"] = [rois_batch_idx]
    helper.append_op(
        type="roi_perspective_transform", inputs=inputs,
        outputs={"Out": [out]},
        attrs={"transformed_height": transformed_height,
               "transformed_width": transformed_width,
               "spatial_scale": spatial_scale})
    return out


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         gt_poly_lens=None):
    """Mask-RCNN mask targets (reference: layers/detection.py:1838 +
    detection/generate_mask_labels_op.cc). Static-shape form: ``gt_segms``
    is a padded [G, P, V, 2] polygon tensor with ``gt_poly_lens`` [G, P]
    vertex counts standing in for the reference's level-3 LoD. Returns
    (mask_rois, roi_has_mask_int32, mask_int32) with all R rows kept,
    foreground first; padding rows carry -1."""
    helper = LayerHelper("generate_mask_labels")
    mask_rois = _out(helper, "float32")
    roi_has_mask = _out(helper, "int32")
    mask_int32 = _out(helper, "int32")
    num = _out(helper, "int32")
    inputs = {"ImInfo": [im_info], "GtClasses": [gt_classes],
              "IsCrowd": [is_crowd], "GtSegms": [gt_segms],
              "Rois": [rois], "LabelsInt32": [labels_int32]}
    if gt_poly_lens is not None:
        inputs["GtPolyLens"] = [gt_poly_lens]
    helper.append_op(
        type="generate_mask_labels", inputs=inputs,
        outputs={"MaskRois": [mask_rois],
                 "RoiHasMaskInt32": [roi_has_mask],
                 "MaskInt32": [mask_int32],
                 "MaskRoisNum": [num]},
        attrs={"num_classes": num_classes, "resolution": resolution})
    return mask_rois, roi_has_mask, mask_int32
