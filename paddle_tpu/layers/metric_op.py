"""Metric layers (reference: python/paddle/fluid/layers/metric_op.py)."""

from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.initializer import ConstantInitializer


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    topk_indices = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [topk_out], "Indices": [topk_indices]},
        attrs={"k": k},
    )
    acc_out = helper.create_variable_for_type_inference(dtype="float32")
    if correct is None:
        correct = helper.create_variable_for_type_inference(dtype="int32")
    if total is None:
        total = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="accuracy",
        inputs={
            "Out": [topk_out],
            "Indices": [topk_indices],
            "Label": [label],
        },
        outputs={
            "Accuracy": [acc_out],
            "Correct": [correct],
            "Total": [total],
        },
    )
    acc_out.stop_gradient = True
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Streaming AUC with persistable stat accumulators
    (reference: layers/metric_op.py auc)."""
    helper = LayerHelper("auc")
    stat_pos = helper.create_global_variable(
        persistable=True,
        name=helper.name + ".stat_pos",
        shape=[num_thresholds + 1],
        dtype="int64",
    )
    helper.set_variable_initializer(stat_pos, ConstantInitializer(0))
    stat_neg = helper.create_global_variable(
        persistable=True,
        name=helper.name + ".stat_neg",
        shape=[num_thresholds + 1],
        dtype="int64",
    )
    helper.set_variable_initializer(stat_neg, ConstantInitializer(0))

    auc_out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(
        type="auc",
        inputs={
            "Predict": [input],
            "Label": [label],
            "StatPos": [stat_pos],
            "StatNeg": [stat_neg],
        },
        outputs={
            "AUC": [auc_out],
            "StatPosOut": [stat_pos],
            "StatNegOut": [stat_neg],
        },
        attrs={"curve": curve, "num_thresholds": num_thresholds},
    )
    auc_out.stop_gradient = True
    return auc_out, [stat_pos, stat_neg]
