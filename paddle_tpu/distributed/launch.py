"""Multi-process launcher (reference: python/paddle/distributed/launch.py —
spawns one worker per device/host setting PADDLE_TRAINER_ID,
PADDLE_TRAINERS_NUM, PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINER_ENDPOINTS;
launch.py:24-53). On TPU one process drives all local chips, so
``nproc_per_node`` defaults to 1 per host; multi-host jobs get the
coordinator env consumed by parallel.env.init_distributed.

Usage:  python -m paddle_tpu.distributed.launch --nproc 2 train.py [args]
"""

import argparse
import os
import subprocess
import sys


def launch_processes(script_args, nproc=1, started_port=6170,
                     node_ip="127.0.0.1", env_extra=None,
                     capture_output=False):
    endpoints = [
        "%s:%d" % (node_ip, started_port + i) for i in range(nproc)
    ]
    pipe = subprocess.PIPE if capture_output else None
    procs = []
    for rank in range(nproc):
        env = dict(os.environ)
        env.update(env_extra or {})
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_TRAINERS_NUM"] = str(nproc)
        env["PADDLE_CURRENT_ENDPOINT"] = endpoints[rank]
        env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(endpoints)
        # rank 0 hosts the PJRT coordinator (the gen_nccl_id analog)
        env["COORDINATOR_ADDRESS"] = endpoints[0]
        # Per-worker telemetry stream: every worker writes its own
        # host-tagged JSONL sink (<base>.h<rank>.jsonl) so a directory
        # of dumps merges into one cross-host report
        # (tools/perf_report.py --merge).
        sink = env.get("PADDLE_TPU_METRICS_SINK")
        if sink:
            from paddle_tpu.observability.export import host_tagged_path

            env["PADDLE_TPU_METRICS_SINK"] = host_tagged_path(sink, rank)
        cmd = [sys.executable] + list(script_args)
        procs.append(subprocess.Popen(cmd, env=env, stdout=pipe,
                                      stderr=pipe))
    return procs


def main():
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nproc", "--nproc_per_node", type=int, default=1)
    parser.add_argument("--started_port", type=int, default=6170)
    parser.add_argument("--node_ip", default="127.0.0.1")
    parser.add_argument("script", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.script:
        parser.error("no training script given")
    procs = launch_processes(args.script, args.nproc, args.started_port,
                             args.node_ip)
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
