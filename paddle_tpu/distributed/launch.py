"""Multi-process launcher + gang supervisor (reference:
python/paddle/distributed/launch.py — spawns one worker per device/host
setting PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM, PADDLE_CURRENT_ENDPOINT,
PADDLE_TRAINER_ENDPOINTS; launch.py:24-53). On TPU one process drives
all local chips, so ``nproc_per_node`` defaults to 1 per host;
multi-host jobs get the coordinator env consumed by
parallel.env.init_distributed.

Supervision (paddle_tpu.resilience): a gang is all-or-nothing — one
dead worker deadlocks its siblings at the next collective, so the
supervisor polls ALL workers, and on the FIRST non-zero exit terminates
the survivors. With a restart budget (``--max-restarts`` /
``PADDLE_TPU_MAX_RESTARTS``) it then re-launches the whole gang after
exponential backoff + jitter, bumping ``PADDLE_TPU_RESTART_COUNT`` and
pointing ``PADDLE_TPU_RECOVERY_CKPT`` at ``--recovery-dir`` so workers
resume from the latest complete checkpoint (resilience.ResilientDriver
picks it up). Every restart is a ``recovery.restart`` telemetry
counter/event.

Elastic shrink (``--max-shrinks`` / ``PADDLE_TPU_MAX_SHRINKS``): a
PERMANENT loss — a worker exiting with faultinject.LOST_EXIT_CODE (45),
or any failure after the restart budget is spent — re-launches the
SURVIVING gang one worker smaller instead of giving up: the job keeps
running on reduced capacity (``health.mesh_shrunk`` event), and workers
see ``PADDLE_TPU_SHRINK_COUNT`` so elastic scripts re-plan their device
mesh (resilience/elastic.py).

Usage:  python -m paddle_tpu.distributed.launch --nproc 2 \
            --max-restarts 3 --recovery-dir /ckpt train.py [args]
"""

import argparse
import os
import subprocess
import sys
import time


def launch_processes(script_args, nproc=1, started_port=6170,
                     node_ip="127.0.0.1", env_extra=None,
                     capture_output=False):
    endpoints = [
        "%s:%d" % (node_ip, started_port + i) for i in range(nproc)
    ]
    pipe = subprocess.PIPE if capture_output else None
    procs = []
    for rank in range(nproc):
        env = dict(os.environ)
        env.update(env_extra or {})
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_TRAINERS_NUM"] = str(nproc)
        env["PADDLE_CURRENT_ENDPOINT"] = endpoints[rank]
        env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(endpoints)
        # rank 0 hosts the PJRT coordinator (the gen_nccl_id analog)
        env["COORDINATOR_ADDRESS"] = endpoints[0]
        # Per-worker telemetry stream: every worker writes its own
        # host-tagged JSONL sink (<base>.h<rank>.jsonl) so a directory
        # of dumps merges into one cross-host report
        # (tools/perf_report.py --merge).
        sink = env.get("PADDLE_TPU_METRICS_SINK")
        if sink:
            from paddle_tpu.observability.export import host_tagged_path

            env["PADDLE_TPU_METRICS_SINK"] = host_tagged_path(sink, rank)
        cmd = [sys.executable] + list(script_args)
        procs.append(subprocess.Popen(cmd, env=env, stdout=pipe,
                                      stderr=pipe))
    return procs


def wait_gang(procs, poll_interval=0.1, term_grace=10.0, monitor=None,
              result=None):
    """Poll ALL workers until the gang resolves; returns the gang rc.

    The seed launcher's sequential ``p.wait()`` hung forever when a
    LATER-indexed worker died while an earlier one blocked on it at a
    collective/barrier. Polling sees the first failure wherever it
    lands; the surviving gang is then terminated (SIGTERM, ``term_grace``
    seconds, then SIGKILL) and the first failing worker's rc propagates.
    All-zero exits return 0.

    With a ``monitor`` (observability.health.HealthMonitor over the
    workers' sink files) the poll loop also watches LIVENESS: when a
    still-running rank is classified hung (heartbeats fresh, step
    counter stalled past the hang timeout) or dead (heartbeats stopped),
    the gang is terminated the same way and ``health.HUNG_EXIT_CODE``
    is returned — a hung collective no longer blocks the job forever.
    Only ranks whose process is still alive are consulted: a worker
    that exited 0 stops heartbeating legitimately.

    ``result`` (optional dict) receives ``failed_rank``/``rc`` for the
    first failing (or first unhealthy) worker — the identity the
    supervisor's gang-shrink path needs to know WHICH capacity was
    lost."""
    while True:
        rcs = [p.poll() for p in procs]
        failed_rank = next(
            (i for i, rc in enumerate(rcs) if rc not in (None, 0)), None)
        if failed_rank is not None:
            if result is not None:
                result["failed_rank"] = failed_rank
                result["rc"] = rcs[failed_rank]
            _terminate_survivors(procs, term_grace)
            return rcs[failed_rank]
        if all(rc == 0 for rc in rcs):
            return 0
        if monitor is not None:
            monitor.poll()
            live = [i for i, rc in enumerate(rcs) if rc is None]
            bad = monitor.unhealthy(ranks=live)
            if bad:
                from paddle_tpu import observability as obs
                from paddle_tpu.observability import health

                desc = ",".join("%d:%s" % (r, s)
                                for r, s in sorted(bad.items()))
                obs.inc("health.hangs_detected")
                # direct tracer event: the incident record must land in
                # the supervisor's sink even with metrics gated off
                obs.tracer.event("health.hang_detected", ranks=desc)
                obs.flush_sink()
                if result is not None:
                    result["failed_rank"] = sorted(bad)[0]
                    result["rc"] = health.HUNG_EXIT_CODE
                print("paddle_tpu.launch: unhealthy rank(s) %s — "
                      "terminating the gang" % desc,
                      file=sys.stderr, flush=True)
                _terminate_survivors(procs, term_grace)
                return health.HUNG_EXIT_CODE
        time.sleep(poll_interval)


def _terminate_survivors(procs, term_grace=10.0):
    live = [p for p in procs if p.poll() is None]
    for p in live:
        try:
            p.terminate()
        except OSError:
            pass
    deadline = time.monotonic() + term_grace
    for p in live:
        try:
            p.wait(timeout=max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            try:
                p.kill()
            except OSError:
                pass
            p.wait()


def supervise(script_args, nproc=1, started_port=6170,
              node_ip="127.0.0.1", env_extra=None, max_restarts=None,
              recovery_dir=None, backoff=None, capture_output=False,
              on_gang=None, heartbeat_ms=None, hang_timeout_s=None,
              max_shrinks=None, stats=None):
    """Launch the gang under supervision; returns the final rc.

    Restarts the WHOLE gang (terminate survivors, backoff, respawn) on
    each failure while ``max_restarts`` (default: the
    PADDLE_TPU_MAX_RESTARTS flag) lasts. Each incarnation's workers see
    ``PADDLE_TPU_RESTART_COUNT`` (0 on the first launch — fault-spec
    entries fire once per job, not once per incarnation) and, when
    ``recovery_dir`` is given, ``PADDLE_TPU_RECOVERY_CKPT`` to resume
    from. ``on_gang(procs, attempt)`` observes each spawned gang
    (tests).

    Elastic shrink: a PERMANENT loss — a worker exiting with
    ``faultinject.LOST_EXIT_CODE`` (45: dead host, failed VM), or any
    failure once the restart budget is spent — relaunches the SURVIVING
    gang one worker smaller instead of giving up, while ``max_shrinks``
    (default: the PADDLE_TPU_MAX_SHRINKS flag, 0) lasts. Each shrink
    emits ``health.mesh_shrunk`` (ungated — the incident record) and
    bumps ``PADDLE_TPU_RESTART_COUNT`` like a restart, so restart-gated
    fault entries do not re-fire; workers additionally see
    ``PADDLE_TPU_SHRINK_COUNT`` so an elastic training script can
    re-plan its device mesh over the surviving capacity
    (resilience/elastic.py). Shrinks do not consume the restart budget.
    A gang exiting ``PREEMPT_EXIT_CODE`` (46 — graceful preemption: the
    worker drained + checkpointed before dying) restarts WITHOUT
    spending the restart budget either: preemption is scheduled
    capacity loss, not a fault. ``stats`` (optional dict) receives
    restarts/shrinks/preempts/final_nproc/lost_ranks on exit.

    Liveness: whenever a metrics sink is configured for the workers,
    heartbeats are auto-enabled (``PADDLE_TPU_HEARTBEAT_MS`` exported
    per worker; ``heartbeat_ms``/the flag override the default) and a
    fresh ``health.HealthMonitor`` per incarnation tails the per-rank
    sink files, so a hung rank restarts the gang the same way a dead
    one does (``hang_timeout_s`` / PADDLE_TPU_HANG_TIMEOUT_S; 0 =
    step-latency-EWMA auto)."""
    from paddle_tpu import flags
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import goodput as goodput_mod
    from paddle_tpu.observability import health
    from paddle_tpu.observability.export import host_tagged_path
    from paddle_tpu.resilience.faultinject import (LOST_EXIT_CODE,
                                                   PREEMPT_EXIT_CODE)
    from paddle_tpu.resilience.retrying import Backoff

    if max_restarts is None:
        max_restarts = int(flags.get_flag("max_restarts"))
    if max_shrinks is None:
        max_shrinks = int(flags.get_flag("max_shrinks"))
    backoff = backoff if backoff is not None else Backoff(
        base=0.5, factor=2.0, cap=30.0, jitter=0.5)
    sink_base = ((env_extra or {}).get("PADDLE_TPU_METRICS_SINK")
                 or os.environ.get("PADDLE_TPU_METRICS_SINK"))
    if heartbeat_ms is not None:
        hb_ms = float(heartbeat_ms)
    else:
        raw = (env_extra or {}).get("PADDLE_TPU_HEARTBEAT_MS")
        hb_ms = float(raw) if raw else float(flags.get_flag("heartbeat_ms"))
        if hb_ms <= 0 and sink_base:
            hb_ms = health.DEFAULT_SUPERVISED_HEARTBEAT_MS
    attempt = 0          # incarnation counter (PADDLE_TPU_RESTART_COUNT)
    restarts = 0         # spent against max_restarts
    shrinks = 0          # spent against max_shrinks
    preempts = 0         # budget-free restarts after graceful preemption
    lost_ranks = []
    # Job-level goodput ledger (observability/goodput.py): gang-up
    # intervals are goodput, the dead air between incarnations is
    # charged to the exit path's badput category — so restart backoff,
    # shrink re-plans, and preemption drains are never silently lost
    # across process boundaries. Fenced by incarnation: a charge tagged
    # with a torn-down gang's attempt is rejected, not mis-booked.
    ledger = goodput_mod.JobLedger(attempt=0)
    gap_since = None     # monotonic ts the last gang exited
    gap_kind = None      # badput category for [gap_since, next launch)
    # Job-level request trace: with tracing enabled (supervisor flags,
    # or trace flags being exported to the workers) the whole job gets
    # ONE trace ID, exported per incarnation via PADDLE_TPU_TRACE_ID so
    # a restarted worker's spans join the same trace — the supervisor
    # itself contributes the between-incarnation restart-gap spans.
    rt = obs.reqtrace
    trace_on = rt.enabled() or any(
        str((env_extra or {}).get(k) or "") not in ("", "0", "0.0")
        for k in ("PADDLE_TPU_TRACE_SAMPLE", "PADDLE_TPU_TRACE_SLOW_MS"))
    job_trace = rt.begin(
        flags_=rt.FLAG_SAMPLED | rt.FLAG_EAGER) if trace_on else None

    def _finish(rc):
        snap = ledger.snapshot()
        if stats is not None:
            stats.update(rc=rc, restarts=restarts, shrinks=shrinks,
                         preempts=preempts, final_nproc=nproc,
                         lost_ranks=list(lost_ranks), goodput=snap,
                         trace_id=(job_trace.trace_id
                                   if job_trace is not None else None))
        # direct tracer event: the job ledger is the incident record a
        # fleet rollup reads, so it lands in the supervisor's sink even
        # with metrics gated off
        obs.tracer.event("goodput.job", rc=rc, attempt=ledger.attempt,
                         wall_ms=round(snap["wall_ms"], 3),
                         goodput_frac=round(snap["goodput_frac"], 6),
                         categories={c: round(m, 3) for c, m in
                                     snap["categories"].items()})
        obs.flush_sink()
        return rc

    while True:
        env = dict(env_extra or {})
        env["PADDLE_TPU_RESTART_COUNT"] = str(attempt)
        env["PADDLE_TPU_SHRINK_COUNT"] = str(shrinks)
        if job_trace is not None:
            rt.export_env(env, job_trace)
        if recovery_dir:
            env["PADDLE_TPU_RECOVERY_CKPT"] = recovery_dir
        monitor = None
        if sink_base and hb_ms > 0:
            # the monitor and the workers must agree on the interval
            env["PADDLE_TPU_HEARTBEAT_MS"] = str(hb_ms)
            monitor = health.HealthMonitor(
                {r: host_tagged_path(sink_base, r) for r in range(nproc)},
                heartbeat_ms=hb_ms, hang_timeout_s=hang_timeout_s)
        t_launch = time.monotonic()
        if gap_since is not None:
            ledger.gap(gap_kind or "restart_downtime", gap_since,
                       t_launch, attempt=attempt)
            if job_trace is not None:
                # the supervisor's own span in the stitched trace: the
                # dead air between the last gang's exit and this
                # incarnation's launch, named with the badput category
                rt.span_event(job_trace, "restart",
                              rt.mono_to_epoch_us(gap_since),
                              (t_launch - gap_since) * 1e6,
                              kind=gap_kind or "restart_downtime",
                              attempt=attempt)
            gap_since = None
        procs = launch_processes(script_args, nproc, started_port,
                                 node_ip, env_extra=env,
                                 capture_output=capture_output)
        if on_gang is not None:
            on_gang(procs, attempt)
        res = {}
        rc = wait_gang(procs, monitor=monitor, result=res)
        gap_since, gap_kind = time.monotonic(), None
        ledger.gang(t_launch, gap_since, attempt=attempt)
        if rc == 0:
            return _finish(0)
        if rc == PREEMPT_EXIT_CODE and preempts < 16:
            # graceful preemption: the worker drained its window and
            # published a blocking checkpoint before exiting — scheduled
            # capacity loss, not a fault, so the restart budget is NOT
            # spent (capped so a preempt storm cannot loop forever)
            preempts += 1
            attempt += 1
            ledger.next_incarnation()
            gap_kind = "preempt_drain"
            obs.inc("recovery.preempt_restart")
            obs.tracer.event("recovery.preempt_restart", attempt=attempt,
                             preempts=preempts)
            obs.flush_sink()
            print("paddle_tpu.launch: gang preempted (rc %s); restarting "
                  "without spending budget [preempt %d]" % (rc, preempts),
                  file=sys.stderr, flush=True)
            time.sleep(backoff.delay(0))
            continue
        permanent = (rc == LOST_EXIT_CODE)
        if ((permanent or restarts >= max_restarts)
                and shrinks < max_shrinks and nproc > 1):
            # the lost rank is never coming back (or restarting has
            # stopped helping): give up on THAT capacity, keep the job
            lost = res.get("failed_rank", nproc - 1)
            lost_ranks.append(lost)
            nproc -= 1
            shrinks += 1
            attempt += 1
            ledger.next_incarnation()
            gap_kind = "shrink_rejit"
            obs.inc("health.mesh_shrunk")
            # direct tracer event: the shrink record must land in the
            # supervisor's sink even with metrics gated off
            obs.tracer.event("health.mesh_shrunk", lost_rank=lost, rc=rc,
                             nproc=nproc, shrinks=shrinks)
            obs.flush_sink()
            print("paddle_tpu.launch: rank %d permanently lost (rc %s); "
                  "shrinking the gang to %d worker(s) [shrink %d/%d]"
                  % (lost, rc, nproc, shrinks, max_shrinks),
                  file=sys.stderr, flush=True)
            time.sleep(backoff.delay(0))
            continue
        if restarts >= max_restarts:
            obs.event("recovery.giveup", rc=rc, restarts=restarts)
            return _finish(rc)
        delay = backoff.delay(restarts)
        restarts += 1
        attempt += 1
        ledger.next_incarnation()
        gap_kind = "restart_downtime"
        obs.inc("recovery.restart")
        obs.event("recovery.restart", rc=rc, attempt=restarts,
                  backoff_s=round(delay, 3))
        print("paddle_tpu.launch: gang failed (rc %s); restart %d/%d "
              "in %.1fs" % (rc, restarts, max_restarts, delay),
              file=sys.stderr, flush=True)
        time.sleep(delay)


def main():
    from paddle_tpu import flags

    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nproc", "--nproc_per_node", type=int, default=1)
    parser.add_argument("--started_port", type=int, default=6170)
    parser.add_argument("--node_ip", default="127.0.0.1")
    parser.add_argument("--max-restarts", type=int, default=None,
                        help="gang restart budget (default: the "
                             "PADDLE_TPU_MAX_RESTARTS flag, 0)")
    parser.add_argument("--max-shrinks", type=int, default=None,
                        help="elastic shrink budget: on a PERMANENT "
                             "worker loss (rc 45, or an exhausted "
                             "restart budget) relaunch the surviving "
                             "gang one worker smaller up to this many "
                             "times (default: the "
                             "PADDLE_TPU_MAX_SHRINKS flag, 0)")
    parser.add_argument("--recovery-dir", default=None,
                        help="checkpoint root exported to workers as "
                             "PADDLE_TPU_RECOVERY_CKPT (default: the "
                             "PADDLE_TPU_RECOVERY_CKPT flag)")
    parser.add_argument("--heartbeat-ms", type=float, default=None,
                        help="worker liveness heartbeat interval "
                             "(default: the PADDLE_TPU_HEARTBEAT_MS "
                             "flag; auto-enabled at 1000ms when a "
                             "metrics sink is configured)")
    parser.add_argument("--hang-timeout", type=float, default=None,
                        help="seconds of step-counter stall before a "
                             "heartbeating rank is hung (default: the "
                             "PADDLE_TPU_HANG_TIMEOUT_S flag; 0 = "
                             "step-latency-EWMA auto)")
    parser.add_argument("script", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.script:
        parser.error("no training script given")
    recovery_dir = args.recovery_dir or flags.get_flag("recovery_ckpt") \
        or None
    sys.exit(supervise(args.script, args.nproc, args.started_port,
                       args.node_ip, max_restarts=args.max_restarts,
                       recovery_dir=recovery_dir,
                       heartbeat_ms=args.heartbeat_ms,
                       hang_timeout_s=args.hang_timeout,
                       max_shrinks=args.max_shrinks))


if __name__ == "__main__":
    main()
