"""Distributed launch + coordination (reference:
python/paddle/distributed/launch.py; the DCN bootstrap role of
gen_nccl_id_op.cc is played by the PJRT coordinator — see
paddle_tpu.parallel.env.init_distributed)."""

from paddle_tpu.distributed.launch import launch_processes  # noqa: F401
