"""Parameter-server runtime: the live transport behind the
DistributeTranspiler's pserver mode.

Reference: the C++ RPC stack — RPCClient/RPCServer with VariableMessage
serde (paddle/fluid/operators/distributed/grpc/grpc_serde.cc,
send_recv.proto.in), request handlers with send/get/fetch barriers
(request_handler_impl.cc), and the listen_and_serv sync loop that waits for
all trainers' gradients, runs one optimizer sub-block per parameter, then
serves Get until the fetch barrier (listen_and_serv_op.cc:107-176
RunSyncLoop). Graceful shutdown mirrors Executor::Close → SendComplete.

This implementation keeps the same protocol state machine over a compact
length-prefixed TCP framing (the image has no grpc); gradients from N
trainers are averaged, then each parameter's optimizer sub-block runs on
the XLA engine.
"""

import socket
import struct
import threading

import numpy as np


# -- framing ---------------------------------------------------------------
#
# Typed wire format — the analog of the reference's VariableMessage proto
# (send_recv.proto.in): a message is a tuple of str / ndarray fields, each
# self-describing. No pickle: nothing received from the socket is ever
# interpreted as code, mirroring the reference's typed zero-copy serde
# (grpc_serde.cc).
#
#   frame   := <Q total_len> payload
#   payload := <B nfields> field*
#   field   := 0x01 <I len> utf8-bytes                    (str)
#            | 0x02 <B dlen> dtype-utf8 <B ndim> <Q>*ndim raw-bytes (ndarray)

_TAG_STR = 1
_TAG_ARR = 2

_ALLOWED_DTYPES = frozenset([
    "float16", "bfloat16", "float32", "float64",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool",
])


def _np_dtype(name):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bfloat16 et al. (ships with jax)

        return np.dtype(getattr(ml_dtypes, name))


def _encode_msg(fields):
    parts = [struct.pack("<B", len(fields))]
    for f in fields:
        if isinstance(f, str):
            b = f.encode("utf-8")
            parts.append(struct.pack("<BI", _TAG_STR, len(b)))
            parts.append(b)
        else:
            arr = np.ascontiguousarray(f)
            # Enforce the wire contract on the sending side too, so a bad
            # call fails fast with a local traceback instead of a remote
            # decode error.
            if arr.dtype.name not in _ALLOWED_DTYPES:
                raise TypeError(
                    "cannot send field of type %s/dtype %s over the "
                    "pserver wire" % (type(f).__name__, arr.dtype))
            dt = arr.dtype.name.encode("utf-8")
            parts.append(struct.pack("<BB", _TAG_ARR, len(dt)))
            parts.append(dt)
            parts.append(struct.pack("<B", arr.ndim))
            parts.append(struct.pack("<%dQ" % arr.ndim, *arr.shape))
            parts.append(arr.tobytes())
    return b"".join(parts)


def _decode_msg(body):
    (nfields,) = struct.unpack_from("<B", body, 0)
    off = 1
    fields = []
    for _ in range(nfields):
        (tag,) = struct.unpack_from("<B", body, off)
        off += 1
        if tag == _TAG_STR:
            (n,) = struct.unpack_from("<I", body, off)
            off += 4
            fields.append(body[off:off + n].decode("utf-8"))
            off += n
        elif tag == _TAG_ARR:
            (dlen,) = struct.unpack_from("<B", body, off)
            off += 1
            dtype = body[off:off + dlen].decode("ascii")
            off += dlen
            if dtype not in _ALLOWED_DTYPES:
                raise ValueError("disallowed dtype on wire: %r" % dtype)
            (ndim,) = struct.unpack_from("<B", body, off)
            off += 1
            shape = struct.unpack_from("<%dQ" % ndim, body, off)
            off += 8 * ndim
            dt = _np_dtype(dtype)
            n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
            arr = np.frombuffer(body[off:off + n], dtype=dt).reshape(shape)
            off += n
            fields.append(arr)
        else:
            raise ValueError("bad wire tag %d" % tag)
    return tuple(fields)


def _send_msg(sock, obj):
    payload = _encode_msg(obj)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock, idle_ok=False):
    """Receive one frame. With ``idle_ok`` (server waiting for a client's
    NEXT request) the wait for the frame header is unbounded — idle
    connections are normal; the deadline still bounds the frame BODY so a
    half-sent frame cannot hang a handler forever."""
    head = _recv_exact(sock, 8, deadline=None if idle_ok
                       else rpc_deadline_seconds())
    if head is None:
        return None
    (n,) = struct.unpack("<Q", head)
    body = _recv_exact(sock, n, deadline=rpc_deadline_seconds())
    if body is None:
        return None
    return _decode_msg(body)


class RpcError(OSError):
    """Typed RPC failure. Peer death is an error the CALLER sees, never a
    bare TypeError in a worker thread (reference: the completion-queue
    status handling of operators/distributed/grpc/grpc_client.cc — a dead
    peer becomes a failed RPC with a message naming the peer)."""


class RpcPeerClosedError(RpcError):
    """The peer closed the connection mid-RPC (EOF before a full reply
    frame arrived)."""


class RpcDeadlineError(RpcError):
    """A peer failed to answer within PADDLE_TPU_RPC_DEADLINE_MS
    (reference: FLAGS_rpc_deadline + the completion-queue timeouts of
    operators/distributed/grpc/grpc_client.cc:64 — a hung peer must fail
    the RPC, not block the trainer forever)."""


def rpc_deadline_seconds():
    from paddle_tpu import flags

    ms = float(flags.get_flag("rpc_deadline_ms"))
    return None if ms <= 0 else ms / 1000.0


def _recv_exact(sock, n, deadline=None):
    import socket as _socket

    prev = sock.gettimeout()
    sock.settimeout(deadline)
    buf = b""
    try:
        while len(buf) < n:
            try:
                chunk = sock.recv(n - len(buf))
            except _socket.timeout:
                raise RpcDeadlineError(
                    "RPC deadline exceeded (%.0f ms) waiting for peer %s"
                    % ((deadline or 0) * 1000.0,
                       sock.getpeername() if sock.fileno() >= 0 else "?"))
            if not chunk:
                return None
            buf += chunk
        return buf
    finally:
        try:
            sock.settimeout(prev)
        except OSError:
            pass


# -- server ----------------------------------------------------------------

class ParameterServer:
    """Executes one trainer-synchronous update loop per batch.

    Protocol (per connection, any number of requests):
      ("send", var_name, ndarray)  — gradient in
      ("batch_barrier",)           — trainer finished sending this batch
      ("get", var_name)            — parameter out (blocks until updated)
      ("complete",)                — trainer shutting down
    """

    def __init__(self, pserver_program, startup_program, endpoint, fanin,
                 scope=None, checkpoint_dir=None):
        import paddle_tpu.fluid as fluid

        self.program = pserver_program
        self.endpoint = endpoint
        self.fanin = fanin
        self.scope = scope if scope is not None else fluid.Scope()
        self.exe = fluid.Executor(fluid.CPUPlace())
        if startup_program is not None:
            self.exe.run(startup_program, scope=self.scope)

        lns = self.program.desc.global_block().ops[-1]
        assert lns.type == "listen_and_serv"
        self.optimize_blocks = list(lns.attrs["optimize_blocks"])
        # Async mode (reference: listen_and_serv_op.cc RunAsyncLoop):
        # each arriving gradient immediately runs its param's optimize
        # block — no barriers, no cross-trainer averaging.
        self.sync_mode = bool(lns.attrs.get("sync_mode", True))
        self._grad_to_block = dict(zip(
            lns.attrs.get("block_grads", []), self.optimize_blocks))

        # Distributed lookup-table shards (reference:
        # distributed/parameter_prefetch.cc + the table optimize block):
        # this server owns rows [start, end) of each table; table-shaped
        # state initialized full-size by the shared startup program is
        # sliced down so no server holds the whole table.
        self.dist_tables = {d["name"]: d
                            for d in lns.attrs.get("dist_tables", [])}
        self._dist_block = {d["block"]: d for d in self.dist_tables.values()}
        for d in self.dist_tables.values():
            for n in d["sliced"]:
                full = self.scope.get(n)
                # slice only FULL-height state (a legacy un-transpiled
                # startup); the per-endpoint startup from
                # get_startup_program already initializes at shard shape
                if (full is not None
                        and np.asarray(full).shape[0] == d["vocab"]):
                    self.scope.set(
                        n, np.asarray(full)[d["start"]:d["end"]])

        self._lock = threading.Condition()
        self._grads = {}          # name -> list of arrays this batch
        self._sparse_grads = {}   # table -> list of (rows, values)
        if checkpoint_dir is not None:
            self.load_checkpoint(checkpoint_dir)
        self._barriers = 0
        self._updated_batch = 0   # generation counter
        self._completed = 0
        self._stop = False
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self._threads = []

    # -- lifecycle ---------------------------------------------------------
    def serve_forever(self):
        accept_thread = threading.Thread(target=self._accept_loop,
                                         daemon=True)
        accept_thread.start()
        with self._lock:
            while not self._stop:
                self._lock.wait(timeout=0.1)
        # Unblock the accept() syscall before closing: closing an fd
        # another thread is blocked in accept() on does NOT cancel the
        # syscall on Linux — the kernel keeps the socket (and the port)
        # alive until accept returns, so a quick restart on the same
        # endpoint would fail with EADDRINUSE.
        try:
            host, port = self.endpoint.rsplit(":", 1)
            socket.create_connection((host, int(port)), timeout=1).close()
        except OSError:
            pass
        accept_thread.join(timeout=2)
        self._sock.close()

    def start(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    # -- request handling --------------------------------------------------
    def _handle(self, conn):
        try:
            self._handle_loop(conn)
        except (ValueError, TypeError, struct.error) as e:
            # Malformed frame (bad tag / disallowed dtype / truncation):
            # reply with an error if the socket still works, then close so
            # the peer sees EOF instead of blocking until its timeout.
            try:
                _send_msg(conn, ("error", "protocol error: %s" % e))
            except OSError:
                pass
        except OSError:
            pass  # peer vanished mid-frame; nothing to reply to
        finally:
            conn.close()

    def _handle_loop(self, conn):
        while True:
            msg = _recv_msg(conn, idle_ok=True)
            if msg is None:
                return
            try:
                if self._dispatch(conn, msg):
                    return
            except OSError:
                raise
            except Exception as e:
                # A handler failure (optimizer block crash, missing var,
                # compile-cache hiccup under load) is THIS request's
                # failure, not the connection's: reply with a typed error
                # the client raises as RpcError, and keep serving. The
                # reference returns a failed grpc::Status per call
                # (request_handler_impl.cc), never tears down the channel.
                _send_msg(conn, ("error", "%s: %s" % (type(e).__name__, e)))

    def _dispatch(self, conn, msg):
        """Handle one request; returns True when the connection is done."""
        kind = msg[0]
        if kind == "send":
            _, name, arr = msg
            if self.sync_mode:
                with self._lock:
                    self._grads.setdefault(name, []).append(arr)
            else:
                # RunAsyncLoop: apply this trainer's gradient now
                # (serialized by the lock — the consistency level of
                # the reference's per-block executor, without
                # cross-trainer barriers)
                with self._lock:
                    self._apply_async_dense(name, arr)
            _send_msg(conn, ("ok",))
        elif kind == "send_sparse":
            _, name, rows, values = msg
            if self.sync_mode:
                with self._lock:
                    self._sparse_grads.setdefault(name, []).append(
                        (rows, values))
            else:
                with self._lock:
                    self._apply_sparse(name, [(rows, values)], scale=1.0)
            _send_msg(conn, ("ok",))
        elif kind == "checkpoint":
            # reference: checkpoint_notify_op.cc:28 — each pserver
            # saves its own shard of the persistables
            _, dirname = msg
            try:
                with self._lock:
                    self.save_checkpoint(dirname)
                _send_msg(conn, ("ok",))
            except OSError as e:
                _send_msg(conn, ("error", "checkpoint failed: %s" % e))
        elif kind == "prefetch":
            # shard-local row gather (reference:
            # request_handler_impl.cc RequestPrefetchHandler); gather
            # BEFORE np.asarray so a device-resident table transfers
            # only the requested rows, not the whole shard. Materialize
            # UNDER the lock: a concurrent optimize block donates the
            # old buffers, and reading a donated jax array raises
            # "Array has been deleted".
            _, name, ids = msg
            with self._lock:
                table = self.scope.get(name)
                # the gather DISPATCH happens under the lock (so it is
                # enqueued before any later optimize block can donate
                # the table buffer); the host transfer runs outside it
                rows_dev = table[ids.astype(np.int64)]
            _send_msg(conn, ("var", np.asarray(rows_dev)))
        elif kind == "batch_barrier":
            if not self.sync_mode:
                # async mode has no barriers (RunAsyncLoop)
                _send_msg(conn, ("ok",))
                return False
            failed = False
            with self._lock:
                self._barriers += 1
                gen = self._updated_batch
                if self._barriers == self.fanin:
                    try:
                        self._run_update()
                        self._updated_batch += 1
                    except Exception:
                        # An update failure while peers are parked in
                        # the wait loop below must not leave the
                        # barrier stuck at fanin — stop the server so
                        # every trainer unblocks; the un-bumped
                        # generation tells them it failed.
                        self._stop = True
                        failed = True
                    self._barriers = 0
                    self._lock.notify_all()
                else:
                    while (self._updated_batch == gen
                           and not self._stop):
                        self._lock.wait(timeout=5)
                    failed = self._stop and self._updated_batch == gen
            if failed:
                _send_msg(conn, ("error", "parameter update failed"))
            else:
                _send_msg(conn, ("ok",))
        elif kind == "get":
            # Take a donation-safe reference UNDER the lock (the
            # round-3 "EOF race" was this read racing an optimize
            # block's buffer donation; the typed RpcError of round 4
            # finally named it): device arrays get a cheap on-device
            # copy enqueued before any later donation can be, host
            # values are rebind-immutable. The expensive
            # device-to-host transfer then runs OUTSIDE the lock so N
            # trainers' param pulls stay concurrent.
            _, name = msg
            with self._lock:
                val = self.scope.get(name)
                if hasattr(val, "addressable_shards"):
                    val = val.copy()
            if val is None:
                raise KeyError("var %r not hosted on %s"
                               % (name, self.endpoint))
            _send_msg(conn, ("var", np.asarray(val)))
        elif kind == "complete":
            with self._lock:
                self._completed += 1
                if self._completed >= self.fanin:
                    self._stop = True
                    self._lock.notify_all()
            _send_msg(conn, ("ok",))
            conn.close()
            return True
        else:
            _send_msg(conn, ("error", "unknown request %r" % kind))
        return False

    def _run_update(self):
        """Average buffered grads, run each optimizer sub-block
        (RunSyncLoop body, listen_and_serv_op.cc:150-160)."""
        avg = {
            name: np.mean(np.stack(vals), axis=0)
            for name, vals in self._grads.items()
        }
        self._grads.clear()
        for name, val in avg.items():
            self.scope.set(name, val)
        sparse = {
            name: pairs for name, pairs in self._sparse_grads.items()
        }
        self._sparse_grads.clear()
        for bidx in self.optimize_blocks:
            dist = self._dist_block.get(bidx)
            if dist is None:
                self.exe.engine.run_block(
                    self.program.desc, bidx, self.scope, feed={},
                    fetch_list=[])
                continue
            # NOTE: the block runs even when no trainer touched this shard
            # this batch — its non-gradient ops (Adam beta-pow advance,
            # momentum velocity decay) are per-step state the local run
            # would also apply; a sentinel-only SelectedRows makes the
            # gradient part a no-op. Sync semantics = mean over trainers:
            # scale by 1/fanin, NOT 1/n_senders (a trainer whose batch hit
            # no row of this shard sends nothing — a zero contribution to
            # the mean, not a smaller denominator).
            self._apply_sparse(dist["name"], sparse.get(dist["name"], []),
                               scale=1.0 / self.fanin, block_idx=bidx)

    def _apply_async_dense(self, grad_name, arr):
        bidx = self._grad_to_block.get(grad_name)
        if bidx is None:
            raise ValueError("no optimize block for gradient %r" % grad_name)
        self.scope.set(grad_name, arr)
        self.exe.engine.run_block(
            self.program.desc, bidx, self.scope, feed={}, fetch_list=[])

    def _apply_sparse(self, table_name, pairs, scale, block_idx=None):
        """Run a distributed table's optimize block on (rows, values)
        pairs; rows bucketed to powers of two with the sentinel row so one
        executable serves all batch sizes."""
        dist = self.dist_tables[table_name]
        if block_idx is None:
            block_idx = dist["block"]
        height = dist["end"] - dist["start"]
        if pairs:
            rows = np.concatenate([r for r, _ in pairs]).astype(np.int64)
            vals = np.concatenate(
                [np.asarray(v) for _, v in pairs]) * scale
        else:
            # shape/dtype metadata only — no table transfer
            table = self.scope.get(table_name)
            rows = np.zeros((0,), np.int64)
            vals = np.zeros((0, table.shape[1]), np.dtype(table.dtype))
        from paddle_tpu.data_feeder import bucketed_length

        bucket = bucketed_length(len(rows), min_bucket=1)
        if bucket > len(rows):
            pad = bucket - len(rows)
            rows = np.concatenate([rows, np.full(pad, height, np.int64)])
            vals = np.concatenate(
                [vals, np.zeros((pad,) + vals.shape[1:], vals.dtype)])
        self.exe.engine.run_block(
            self.program.desc, block_idx, self.scope,
            feed={table_name + "@GRAD@ROWS": rows,
                  table_name + "@GRAD@VALUES": vals},
            fetch_list=[])

    # -- distributed checkpointing -----------------------------------------
    def _owned_persistables(self):
        """Persistable vars this server's optimize blocks touch — its shard
        of the model (reference: io.py:261 _save_distributed_persistables
        gathers exactly the pserver-side vars)."""
        names = set()
        gb = self.program.desc.global_block()
        for bidx in self.optimize_blocks:
            bd = self.program.desc.block(bidx)
            for op in bd.ops:
                for n in op.input_arg_names() + op.output_arg_names():
                    vd = gb.find_var_recursive(n)
                    if vd is not None and vd.persistable:
                        names.add(n)
        return sorted(names)

    def _checkpoint_path(self, dirname):
        import os

        tag = self.endpoint.replace(":", "_").replace("/", "_")
        return os.path.join(dirname, "pserver_%s.npz" % tag)

    def save_checkpoint(self, dirname):
        """Save this server's shard (reference: checkpoint_notify_op.cc:28
        -> RequestCheckpointHandler saving the owned vars)."""
        import os

        os.makedirs(dirname, exist_ok=True)
        arrays = {}
        for n in self._owned_persistables():
            v = self.scope.get(n)
            if v is not None:
                arrays[n] = np.asarray(v)
        # record each table shard's row offset so loaders reassemble in
        # ROW order, not in checkpoint-filename order
        for d in self.dist_tables.values():
            for n in d.get("sliced", []):
                if n in arrays:
                    arrays[n + "@SHARD_START"] = np.asarray(
                        d["start"], np.int64)
        np.savez(self._checkpoint_path(dirname), **arrays)

    def load_checkpoint(self, dirname):
        import os

        path = self._checkpoint_path(dirname)
        if not os.path.exists(path):
            raise FileNotFoundError(
                "no checkpoint for %s at %s" % (self.endpoint, path))
        with np.load(path) as data:
            for n in data.files:
                self.scope.set(n, data[n])


# -- client ----------------------------------------------------------------

def _connect_with_retry(ep, deadline=None):
    """Connect to a pserver endpoint under the shared retry policy
    (resilience.retrying): a trainer routinely starts BEFORE its
    pservers bind — or reconnects while a supervised gang restart is
    still re-binding the port — so connection-refused is a schedule
    fact, not an error, until the overall deadline says otherwise
    (reference: the gRPC channel's reconnect backoff the C++ client
    leans on, grpc_client.cc). The deadline defaults to
    FLAGS rpc_deadline (60s when the deadline flag is disabled)."""
    from paddle_tpu import observability as obs
    from paddle_tpu.resilience.retrying import Backoff, retry_call

    if deadline is None:
        deadline = rpc_deadline_seconds() or 60.0
    host, port = ep.rsplit(":", 1)

    def _on_retry(e, attempt, delay):
        obs.inc("recovery.rpc_connect_retry")
        obs.event("rpc.connect_retry", endpoint=ep, attempt=attempt,
                  error=str(e)[:200])

    try:
        return retry_call(
            socket.create_connection, (host, int(port)), timeout=5,
            retry_on=(ConnectionRefusedError, ConnectionResetError,
                      ConnectionAbortedError, socket.timeout),
            deadline=deadline,
            backoff=Backoff(base=0.05, factor=2.0, cap=2.0, jitter=0.5),
            on_retry=_on_retry)
    except OSError as e:
        raise RpcError(
            "cannot connect to pserver %s within %.0fs: %s"
            % (ep, deadline, e)) from e


class PSClient:
    """Trainer-side RPC client (reference: distributed/rpc_client.h:32 —
    AsyncSendVar/AsyncGetVar + barriers, SendComplete). Connects
    through the shared backoff/deadline policy so trainer-before-server
    startup ordering and gang restarts resolve instead of crashing."""

    def __init__(self, endpoints, connect_deadline=None):
        self._socks = {}
        for ep in endpoints:
            self._socks[ep] = _connect_with_retry(ep, connect_deadline)

    def _reply(self, ep, expect, idle_ok=False):
        """One reply frame, or a typed RpcError. EOF (server died or shut
        the connection mid-RPC) and wrong-kind replies both name the peer
        so the failure is diagnosable from the trainer side."""
        msg = _recv_msg(self._socks[ep], idle_ok=idle_ok)
        if msg is None:
            raise RpcPeerClosedError(
                "pserver %s closed the connection before replying" % ep)
        if msg[0] == "error":
            raise RpcError("pserver %s: %s" % (ep, msg[1]))
        if msg[0] != expect:
            raise RpcError("pserver %s replied %r, expected %r"
                           % (ep, msg[0], expect))
        return msg

    def _fanout_replies(self, expect, idle_ok=False):
        """Drain one reply from EVERY endpoint before raising, so one
        server's failure cannot leave another's unread reply on the wire
        and desync that connection for every later RPC."""
        errors = []
        for ep in self._socks:
            try:
                self._reply(ep, expect, idle_ok=idle_ok)
            except OSError as e:
                errors.append(e)
        if errors:
            if len(errors) == 1:
                raise errors[0]
            raise RpcError("; ".join(str(e) for e in errors))

    def send_var(self, ep, name, arr):
        _send_msg(self._socks[ep], ("send", name, np.asarray(arr)))
        self._reply(ep, "ok")

    def batch_barrier(self):
        for s in self._socks.values():
            _send_msg(s, ("batch_barrier",))
        # barrier completion waits on the SLOWEST peer trainer (a
        # straggler's first-step compile can exceed any RPC deadline)
        # — unbounded like the reference's sync barrier
        self._fanout_replies("ok", idle_ok=True)

    def get_var(self, ep, name):
        _send_msg(self._socks[ep], ("get", name))
        return self._reply(ep, "var")[1]

    def prefetch(self, ep, name, local_ids):
        """Rows of a table shard by shard-local id (reference:
        parameter_prefetch.cc prefetch_recv)."""
        _send_msg(self._socks[ep],
                  ("prefetch", name, np.asarray(local_ids, np.int64)))
        return self._reply(ep, "var")[1]

    def send_sparse(self, ep, name, local_rows, values):
        _send_msg(self._socks[ep],
                  ("send_sparse", name,
                   np.asarray(local_rows, np.int64),
                   np.asarray(values)))
        self._reply(ep, "ok")

    def checkpoint_notify(self, dirname):
        """Ask every pserver to save its shard (reference:
        checkpoint_notify_op.cc:28)."""
        for s in self._socks.values():
            _send_msg(s, ("checkpoint", dirname))
        self._fanout_replies("ok")

    def send_complete(self):
        for s in self._socks.values():
            try:
                _send_msg(s, ("complete",))
                _recv_msg(s)
            except OSError:
                pass
            s.close()

    def close(self):
        """Close the sockets WITHOUT signalling trainer completion — for
        read-only clients (an evaluator pulling params must not consume a
        completion slot and stop a live cluster)."""
        for s in self._socks.values():
            try:
                s.close()
            except OSError:
                pass


class DistTrainer:
    """Runs a transpiled trainer program: compiled fwd/bwd on the engine,
    then send-grads → barrier → recv-params over the client (the role of
    the send/recv/fetch_barrier ops in the reference trainer program)."""

    def __init__(self, trainer_program, transpiler, scope=None):
        import paddle_tpu.fluid as fluid

        self.scope = scope if scope is not None else fluid.Scope()
        self.exe = fluid.Executor()
        # send/recv markers carry the routing; the compiled program runs
        # without them (the transport is this class)
        self._sends = []   # (grad_name, endpoint, wire_name, rows|None)
        self._recvs = []   # (param_name, endpoint, wire_name, rows|None)
        self.program = trainer_program.clone()
        block = self.program.desc.global_block()
        kept = []
        # distributed lookup tables: host-side prefetch/sparse-send per
        # table (the marker ops stay in the program — they are real
        # compiled ops; the transpiler records the routing)
        self._dist = []    # (table, ids_var, pref_var, vocab, shards)
        self._transpiler = transpiler
        dist_tables = getattr(transpiler, "_dist_tables", {})
        for op in block.ops:
            if op.type == "send":
                self._sends.append(
                    (op.inputs["X"][0], op.attrs["endpoints"][0],
                     op.attrs.get("wire", op.inputs["X"][0]),
                     op.attrs.get("rows")))
            elif op.type == "recv":
                self._recvs.append(
                    (op.outputs["Out"][0], op.attrs["endpoints"][0],
                     op.attrs.get("wire", op.outputs["Out"][0]),
                     op.attrs.get("rows")))
            else:
                if op.type == "distributed_lookup":
                    wname = op.attrs["table_name"]
                    self._dist.append(
                        (wname, op.inputs["Ids"][0],
                         op.inputs["Prefetched"][0],
                         dist_tables[wname]["vocab"],
                         dist_tables[wname]["shards"]))
                kept.append(op)
        block.ops = kept
        self.program._bump_version()
        eps = sorted({ep for _, ep, _, _ in self._sends + self._recvs}
                     | {ep for *_, shards in self._dist
                        for ep, _, _ in shards})
        self.client = PSClient(eps)

    def run_startup(self, startup_program):
        self.exe.run(startup_program, scope=self.scope)
        # a caller may pass the un-transpiled startup; drop the full table
        # AND its table-shaped optimizer state (Adam moments etc.) it
        # initialized (get_trainer_startup_program avoids creating them)
        if self._dist:
            for name in self._transpiler.table_state_var_names():
                self.scope.erase(name)

    def pull_params(self):
        """Initial sync so all trainers start from the pserver's params."""
        self._recv_all()

    def _recv_all(self):
        """Fetch every param — whole vars directly, sliced vars assembled
        from their row blocks (reference: recv + concat of VarBlocks)."""
        for name, ep, wire, rows in self._recvs:
            part = self.client.get_var(ep, wire)
            if rows is None:
                self.scope.set(name, part)
                continue
            cur = self.scope.get(name)
            cur = np.array(cur) if cur is not None else None
            if cur is None or cur.shape[0] < rows[1]:
                raise RuntimeError(
                    "sliced param %r not materialized trainer-side" % name)
            cur[rows[0]:rows[1]] = part
            self.scope.set(name, cur)

    def run(self, feed, fetch_list):
        # -- prefetch distributed-table rows for this batch's ids ---------
        # (reference: parameter_prefetch.cc — split ids by shard, RPC each
        # owner, merge rows back in id order; deduplicated like
        # merge_ids_op so each unique id crosses the wire once)
        feed = dict(feed)
        dist_ctx = []
        for wname, ids_var, pref_var, vocab, shards in self._dist:
            if ids_var not in feed:
                raise ValueError(
                    "distributed lookup table %r needs its ids %r in the "
                    "feed" % (wname, ids_var))
            flat = np.asarray(feed[ids_var]).reshape(-1)
            if flat.size and (flat.min() < 0 or flat.max() >= vocab):
                # the local lookup_table clamps via gather; silently
                # dropping unowned ids here would train zero embeddings
                raise ValueError(
                    "ids for distributed table %r out of range [0, %d): "
                    "min=%d max=%d" % (wname, vocab, flat.min(),
                                       flat.max()))
            uniq, inv = np.unique(flat, return_inverse=True)
            rows = None
            for ep, start, end in shards:
                m = (uniq >= start) & (uniq < end)
                if not m.any():
                    continue
                part = self.client.prefetch(ep, wname, uniq[m] - start)
                if rows is None:
                    rows = np.zeros((len(uniq), part.shape[-1]),
                                    part.dtype)
                rows[m] = part
            assert rows is not None, "no shard owned any id"
            feed[pref_var] = rows[inv]
            dist_ctx.append((wname, pref_var + "@GRAD", uniq, inv, shards))

        grad_names = sorted({g for g, *_ in self._sends})
        sparse_fetch = [g for _, g, *_ in dist_ctx]
        outs = self.exe.run(
            self.program, feed=feed,
            fetch_list=list(fetch_list) + grad_names + sparse_fetch,
            scope=self.scope)
        n_fetch = len(fetch_list)
        grads = dict(zip(grad_names + sparse_fetch, outs[n_fetch:]))
        for gname, ep, wire, rows in self._sends:
            arr = np.asarray(grads[gname])
            if rows is not None:
                arr = arr[rows[0]:rows[1]]
            self.client.send_var(ep, wire, arr)
        # -- sparse grads back to the shard owners, merged per unique id --
        for wname, gname, uniq, inv, shards in dist_ctx:
            vals = np.asarray(grads[gname])
            merged = np.zeros((len(uniq), vals.shape[-1]), vals.dtype)
            np.add.at(merged, inv, vals)
            for ep, start, end in shards:
                m = (uniq >= start) & (uniq < end)
                if not m.any():
                    continue
                self.client.send_sparse(ep, wname, uniq[m] - start,
                                        merged[m])
        self.client.batch_barrier()
        self._recv_all()
        return outs[:n_fetch]

    def save_checkpoint(self, dirname):
        """Distributed checkpoint: every pserver saves its own shard
        (reference: io.py:261 _save_distributed_persistables +
        checkpoint_notify)."""
        self.client.checkpoint_notify(dirname)

    def close(self):
        self.client.send_complete()
