"""``paddle_tpu.fluid`` — the Fluid-compatible namespace.

Lets reference-era scripts switch with one import line:
``import paddle_tpu.fluid as fluid``
(reference API surface: python/paddle/fluid/__init__.py).
"""

from paddle_tpu import ops as _ops  # noqa: F401  (registers all lowerings)
from paddle_tpu import layers  # noqa: F401
from paddle_tpu import initializer  # noqa: F401
from paddle_tpu import optimizer  # noqa: F401
from paddle_tpu import regularizer  # noqa: F401
from paddle_tpu import clip  # noqa: F401
from paddle_tpu import unique_name  # noqa: F401
from paddle_tpu import metrics  # noqa: F401
from paddle_tpu import observability  # noqa: F401
from paddle_tpu import profiler  # noqa: F401

from paddle_tpu.framework import (  # noqa: F401
    Program,
    Variable,
    Operator,
    program_guard,
    name_scope,
    default_main_program,
    default_startup_program,
    grad_var_name,
)
from paddle_tpu.core_shim import (  # noqa: F401
    LoDTensor,
    LoDTensorArray,
)
from paddle_tpu import backward  # noqa: F401
from paddle_tpu import flags  # noqa: F401
from paddle_tpu.flags import set_flags  # noqa: F401
from paddle_tpu import recordio_writer  # noqa: F401
from paddle_tpu import dlpack  # noqa: F401
from paddle_tpu import nets  # noqa: F401


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """(reference: lod_tensor.py create_lod_tensor). For list data the
    per-row lengths must agree with the LAST level of
    ``recursive_seq_lens`` (the reference asserts the same)."""
    import numpy as np

    from paddle_tpu.core_shim import LoDTensor as _LT

    if isinstance(data, list):
        row_lens = [len(np.asarray(r).reshape(-1)) for r in data]
        if recursive_seq_lens and                 list(recursive_seq_lens[-1]) != row_lens:
            raise ValueError(
                "create_lod_tensor: recursive_seq_lens[-1]=%s does not "
                "match the data row lengths %s"
                % (recursive_seq_lens[-1], row_lens))
        arr = np.concatenate(
            [np.asarray(row).reshape(-1, 1) for row in data], axis=0)
        t = _LT()
        t.set(arr, place)
        t.set_recursive_sequence_lengths(
            recursive_seq_lens or [row_lens])
        return t
    t = _LT()
    t.set(np.asarray(data), place)
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place,
                                low, high):
    """(reference: lod_tensor.py create_random_int_lodtensor)."""
    import numpy as np

    total = sum(recursive_seq_lens[-1])
    arr = np.random.randint(low, high + 1,
                            [total] + list(base_shape)).astype("int64")
    return create_lod_tensor(arr, recursive_seq_lens, place)
from paddle_tpu.executor import Executor, global_scope, scope_guard  # noqa: F401
from paddle_tpu.core.scope import Scope  # noqa: F401
from paddle_tpu.platform import (  # noqa: F401
    CPUPlace,
    TPUPlace,
    CUDAPlace,
    CUDAPinnedPlace,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
)
from paddle_tpu.layers.control_flow import (  # noqa: F401
    While,
    StaticRNN,
    Switch,
)
from paddle_tpu.param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from paddle_tpu.backward import append_backward, calc_gradient  # noqa: F401
from paddle_tpu.data_feeder import DataFeeder  # noqa: F401
from paddle_tpu.data_feed_desc import DataFeedDesc  # noqa: F401
from paddle_tpu.async_executor import AsyncExecutor  # noqa: F401
from paddle_tpu.compiler import CompiledProgram  # noqa: F401
from paddle_tpu.parallel_executor import (  # noqa: F401
    ParallelExecutor,
    ExecutionStrategy,
    BuildStrategy,
)
from paddle_tpu import io  # noqa: F401
from paddle_tpu import imperative  # noqa: F401
from paddle_tpu import transpiler  # noqa: F401
from paddle_tpu.transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
    InferenceTranspiler,
    memory_optimize,
    release_memory,
)
from paddle_tpu import contrib  # noqa: F401
from paddle_tpu import recordio  # noqa: F401
from paddle_tpu import reader  # noqa: F401
from paddle_tpu.executor import EOFException  # noqa: F401
from paddle_tpu.layers.io import py_reader, PyReader  # noqa: F401
from paddle_tpu.io import (  # noqa: F401
    save_params,
    save_persistables,
    load_params,
    load_persistables,
    save_inference_model,
    load_inference_model,
)
from paddle_tpu import core_shim as core  # noqa: F401

# default_startup_program must be importable as fluid.default_startup_program
__all__ = [
    "layers", "initializer", "optimizer", "regularizer", "clip",
    "Program", "Variable", "Operator", "program_guard",
    "default_main_program", "default_startup_program",
    "Executor", "global_scope", "scope_guard", "Scope",
    "CPUPlace", "TPUPlace", "CUDAPlace", "ParamAttr",
    "append_backward", "DataFeeder", "CompiledProgram", "ParallelExecutor",
    "io", "core", "metrics", "profiler",
]
