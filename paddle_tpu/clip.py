"""Gradient clipping (reference: python/paddle/fluid/clip.py —
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm,
ErrorClipByValue; set via set_gradient_clip or ParamAttr.gradient_clip)."""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "ErrorClipByValue",
    "set_gradient_clip",
    "append_gradient_clip_ops",
]

_clip_attr = None


class BaseGradientClipAttr:
    def _process(self, params_grads):
        raise NotImplementedError


class ErrorClipByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _clip_one(self, param, grad):
        block = grad.block
        helper = LayerHelper("clip_grad", block=block)
        out = helper.create_variable_for_type_inference(dtype=grad.dtype)
        block.append_op(
            type="clip",
            inputs={"X": [grad]},
            outputs={"Out": [out]},
            attrs={"min": self.min, "max": self.max},
        )
        return out

    def _process(self, params_grads):
        return [
            (p, self._clip_one(p, g) if g is not None else None)
            for p, g in params_grads
        ]


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_one(self, param, grad):
        block = grad.block
        helper = LayerHelper("clip_grad_norm", block=block)
        out = helper.create_variable_for_type_inference(dtype=grad.dtype)
        block.append_op(
            type="clip_by_norm",
            inputs={"X": [grad]},
            outputs={"Out": [out]},
            attrs={"max_norm": self.clip_norm},
        )
        return out

    def _process(self, params_grads):
        return [
            (p, self._clip_one(p, g) if g is not None else None)
            for p, g in params_grads
        ]


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process(self, params_grads):
        live = [(p, g) for p, g in params_grads if g is not None]
        if not live:
            return params_grads
        block = live[0][1].block
        helper = LayerHelper("global_norm_clip", block=block)
        sq_norms = []
        for _, g in live:
            sq = helper.create_variable_for_type_inference(dtype=g.dtype)
            block.append_op(
                type="squared_l2_norm",
                inputs={"X": [g]},
                outputs={"Out": [sq]},
            )
            sq_norms.append(sq)
        total = helper.create_variable_for_type_inference(dtype="float32")
        block.append_op(
            type="sum", inputs={"X": sq_norms}, outputs={"Out": [total]}
        )
        global_norm = helper.create_variable_for_type_inference(dtype="float32")
        block.append_op(
            type="sqrt", inputs={"X": [total]}, outputs={"Out": [global_norm]}
        )
        # scale = clip_norm / max(global_norm, clip_norm)
        clipped = helper.create_variable_for_type_inference(dtype="float32")
        block.append_op(
            type="clip",
            inputs={"X": [global_norm]},
            outputs={"Out": [clipped]},
            attrs={"min": self.clip_norm, "max": 3.4e38},
        )
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            scaled = helper.create_variable_for_type_inference(dtype=g.dtype)
            num = helper.create_variable_for_type_inference(dtype=g.dtype)
            block.append_op(
                type="scale",
                inputs={"X": [g]},
                outputs={"Out": [num]},
                attrs={"scale": self.clip_norm},
            )
            block.append_op(
                type="elementwise_div",
                inputs={"X": [num], "Y": [clipped]},
                outputs={"Out": [scaled]},
                attrs={"axis": -1},
            )
            out.append((p, scaled))
        return out


def set_gradient_clip(clip, param_list=None, program=None):
    global _clip_attr
    _clip_attr = clip
    if param_list is not None:
        for p in param_list:
            if hasattr(p, "gradient_clip_attr"):
                p.gradient_clip_attr = clip


def append_gradient_clip_ops(params_grads):
    # Per-param clip attrs take priority; else the global one.
    global_clip = _clip_attr
    per_param = {}
    for p, g in params_grads:
        attr = getattr(p, "gradient_clip_attr", None)
        clip = attr or global_clip
        per_param.setdefault(id(clip), (clip, []))[1].append((p, g))
    out = []
    for clip, pg in per_param.values():
        if clip is None:
            out.extend(pg)
        else:
            out.extend(clip._process(pg))
    return out
