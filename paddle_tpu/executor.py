"""Executor — the user-facing run loop (reference:
python/paddle/fluid/executor.py — Executor:262, run:451, program cache +
feed/fetch injection :319-363). Dispatches whole blocks to the XLA engine;
CompiledProgram runs go through the SPMD path (compiler.py)."""

import numpy as np

from paddle_tpu.core.scope import Scope
from paddle_tpu.engine.executor import Engine
from paddle_tpu.framework import Program, default_main_program
from paddle_tpu.platform import CPUPlace, default_accelerator_place

_global_scope = Scope()


class EOFException(Exception):
    """Raised when a PyReader-fed program exhausts its epoch
    (reference: fluid.core.EOFException from the C++ reader ops)."""


def global_scope():
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        global _global_scope
        old = _global_scope
        _global_scope = scope
        try:
            yield
        finally:
            _global_scope = old

    return _guard()


def _as_feed_dict(feed):
    import jax

    if feed is None:
        return {}
    if isinstance(feed, dict):
        return {
            k: v if isinstance(v, jax.Array) else np.asarray(v)
            for k, v in feed.items()
        }
    raise TypeError("feed must be a dict of name -> ndarray")


class Executor:
    def __init__(self, place=None):
        self.place = place if place is not None else default_accelerator_place()
        self.engine = Engine(self.place)

    def close(self):
        """Graceful shutdown (reference: executor.py close — notifies
        pservers). The in-flight dispatch window is dropped without
        materializing (nothing will read the placeholders) and engine
        caches are cleared."""
        self.engine.discard_window()
        self.engine._cache.clear()

    def sync(self):
        """Barrier for multi-step dispatch (``run(...,
        dispatch_steps=N)``): retires every in-flight step, resolving
        the outstanding ``DeferredFetch`` placeholders. Deferred
        ``check_nan_inf`` verdicts raise here, oldest step first, each
        naming its ORIGINAL step index. A no-op when nothing is in
        flight (dispatch_steps=1 loops never pay it)."""
        self.engine.sync()

    def cost_analysis(self, program=None, feed=None, fetch_list=None,
                      scope=None, accumulate_steps=1, remat_segments=0,
                      opt_level=None):
        """XLA's cost and memory analysis of the compiled step — the
        roofline workflow as a first-class API (round 5 used it to pin
        ResNet-50 at 145.5 GB/step against 670 GB/s achieved; see
        MFU_r05.md). Compiles the same executable ``run`` would (without
        executing — no state is mutated, no cache entry added) and
        returns::

            {"bytes_accessed": float, "flops": float,
             "cost": <full XLA cost dict>,
             "memory": <CompiledMemoryStats>}

        Divide ``bytes_accessed`` by the measured step time for achieved
        HBM bandwidth; compare ``flops``/time to the chip's peak for MFU.
        ``accumulate_steps`` must match the value passed to ``run`` or
        the analysis describes a different (single-micro-batch)
        executable. The scope must hold initialized state (run the
        startup program first). Analysis availability depends on the
        backend; fields whose query fails are None."""
        from paddle_tpu.compiler import CompiledProgram

        scope = scope if scope is not None else global_scope()
        if program is None:
            program = default_main_program()
        if isinstance(program, CompiledProgram):
            raise TypeError(
                "cost_analysis takes the plain Program (SPMD-compiled "
                "program analysis is not supported yet); pass the "
                "program you built, not the CompiledProgram wrapper")
        feed = _as_feed_dict(feed)
        fetch_names = [
            f.name if hasattr(f, "name") else str(f)
            for f in (fetch_list or [])
        ]
        block = program.desc.block(0)
        feed_names, feed_values = self.engine._coerce_feed(block, feed)
        # the SHARED engine cache: analysis compiles exactly the
        # executable a subsequent run reuses, and reuses one a prior run
        # compiled
        compiled = self.engine.get_compiled(
            program.desc, 0, feed_names, feed_values, fetch_names,
            getattr(program, "_is_test", False), True,
            getattr(program, "_amp", False), accumulate_steps,
            remat_segments=remat_segments, opt_level=opt_level,
            scope=scope)
        mutated = [self.engine._state_value(scope, n)
                   for n in compiled.mutated_names]
        readonly = [self.engine._state_value(scope, n)
                    for n in compiled.readonly_names]
        comp = compiled.jitted.lower(
            feed_values, mutated, readonly,
            (np.uint32(0), np.uint32(1))).compile()
        out = {"bytes_accessed": None, "flops": None, "cost": None,
               "memory": None}
        try:
            cost = comp.cost_analysis()
            if isinstance(cost, list):
                cost = cost[0]
            out["cost"] = dict(cost)
            out["bytes_accessed"] = float(cost.get("bytes accessed", 0.0))
            out["flops"] = float(cost.get("flops", 0.0))
        except Exception:  # pragma: no cover - backend-dependent
            pass
        try:
            out["memory"] = comp.memory_analysis()
        except Exception:  # pragma: no cover - backend-dependent
            pass
        if out["memory"] is not None:
            # The analysis feeds the same HBM gauges the engine seams
            # record, so a roofline pass and a training run publish one
            # consistent hbm.compile_* series.
            from paddle_tpu import observability as obs

            if obs.enabled():
                obs.memory.record_compile_stats(out["memory"],
                                                label="cost_analysis")
        return out

    def run(self, program=None, feed=None, fetch_list=None, feed_var_name="feed",
            fetch_var_name="fetch", scope=None, return_numpy=True,
            use_program_cache=True, accumulate_steps=1, remat_segments=0,
            verify=None, opt_level=None, mesh=None, shard_rules=None,
            data_axes=("dp",), dispatch_steps=None):
        """``accumulate_steps=k`` runs the feed as k micro-batches through a
        compiled scan with one optimizer update on the averaged gradients —
        the batch-merge capability (reference:
        framework/ir/multi_batch_merge_pass.cc; see
        engine/lowering.py lower_block_accumulated).

        ``remat_segments=s`` compiles the training step with the forward
        partitioned into ``s`` ``jax.checkpoint`` segments and gradients
        taken through them — only segment-boundary activations survive to
        the backward pass, trading recompute for the activation memory
        that bounds long-context/large-batch training (see
        engine/lowering.py lower_block_remat; the TPU-native form of the
        reference's memory-optimization passes).

        ``verify=True`` (default: the PADDLE_TPU_VERIFY flag) statically
        verifies the program pre-lowering — once per compiled executable
        — and raises ``analysis.VerificationError`` on ERROR-severity
        findings (see paddle_tpu.analysis).

        ``opt_level`` (default: the PADDLE_TPU_OPT_LEVEL flag) selects the
        desc-level transform pipeline applied once per compiled
        executable — 0 off, 1 attention-pattern→flash rewrite, 2 + fusion
        / constant folding / CSE (see paddle_tpu.analysis.transforms).

        ``mesh``/``shard_rules``/``data_axes`` select the GSPMD path on a
        plain Program: the step is jitted with ``jax.sharding`` in/out
        specs over the mesh — feeds batch-sharded over ``data_axes``,
        state laid out per the ``parallel.sharding.ShardingRules`` table
        (replicated when no rule matches) — and XLA's partitioner
        derives every gradient collective in-graph (no pserver
        round-trip). Default: the ``PADDLE_TPU_MESH`` flag when set,
        else single-device compilation. A 1-device mesh is bit-identical
        to no mesh.

        ``dispatch_steps=N`` (default: the ``PADDLE_TPU_DISPATCH_STEPS``
        flag) enqueues up to N steps into the engine's async dispatch
        window without blocking on device results: each run returns
        ``DeferredFetch`` placeholders immediately (shape/dtype readable
        without blocking; any host use — ``np.asarray``, ``float()`` —
        resolves them), the only host sync in steady state is the retire
        of the OLDEST in-flight step, and ``Executor.sync()`` is the
        barrier that drains the window. Bit-exact with
        ``dispatch_steps=1``: the same executables run with the same rng
        counters — only host-materialization timing changes. With
        ``check_nan_inf`` the verdict is deferred to retire time and
        reports the original step index; scope state past a blown-up
        step may be non-finite until a rollback restores it (pair deep
        windows with ``resilience.ResilientDriver``).

        Every run is wrapped in a top-level ``executor.run`` telemetry
        span when ``PADDLE_TPU_METRICS`` is up (paddle_tpu.observability)
        — the outermost host lane of the step timeline."""
        from paddle_tpu import observability as obs
        from paddle_tpu.compiler import CompiledProgram

        with obs.span("executor.run"):
            try:
                return self._run_impl(
                    program=program, feed=feed, fetch_list=fetch_list,
                    scope=scope, return_numpy=return_numpy,
                    accumulate_steps=accumulate_steps,
                    remat_segments=remat_segments, verify=verify,
                    opt_level=opt_level, mesh=mesh,
                    shard_rules=shard_rules,
                    data_axes=data_axes, dispatch_steps=dispatch_steps)
            finally:
                # goodput ledger step boundary: everything since the
                # last seam mark (compile / input_wait / host_sync /
                # driver charges) was forward progress — charge it as
                # compute and refresh the goodput.*/mfu.* gauges. The
                # widest per-step envelope, so inter-seam host work
                # counts as compute, not idle.
                obs.goodput.step_boundary()

    def _run_impl(self, program=None, feed=None, fetch_list=None,
                  scope=None, return_numpy=True, accumulate_steps=1,
                  remat_segments=0, verify=None, opt_level=None,
                  mesh=None, shard_rules=None, data_axes=("dp",),
                  dispatch_steps=None):
        from paddle_tpu.compiler import CompiledProgram

        scope = scope if scope is not None else global_scope()
        fetch_list = fetch_list or []
        explicit_depth = dispatch_steps is not None
        if dispatch_steps is None:
            # zero-code-change entry, like PADDLE_TPU_MESH: the flag
            # turns an existing training loop into a windowed one
            from paddle_tpu import flags

            dispatch_steps = int(flags.get_flag("dispatch_steps"))
        dispatch_steps = max(1, int(dispatch_steps))

        if isinstance(program, CompiledProgram):
            if dispatch_steps > 1 and explicit_depth:
                raise NotImplementedError(
                    "dispatch_steps>1 is not supported on the "
                    "CompiledProgram (legacy SPMD) path; use the plain "
                    "Program with mesh=/PADDLE_TPU_MESH — the GSPMD "
                    "path composes with the dispatch window")
            if remat_segments:
                raise NotImplementedError(
                    "remat_segments is not supported on the CompiledProgram "
                    "(SPMD) path yet; pass the plain Program, or combine "
                    "sharding with accumulate_steps for memory headroom")
            return program._run(self, feed, fetch_list, scope, return_numpy,
                                verify=verify, opt_level=opt_level)

        if program is None:
            program = default_main_program()

        if feed is None and getattr(program, "_py_readers", None):
            # decoupled feeding: pull the next prefetched batch
            feed = {}
            for rdr in program._py_readers:
                nxt = rdr.next_feed()
                if nxt is None:
                    raise EOFException(
                        "py_reader epoch exhausted; call reader.start() "
                        "for the next epoch")
                feed.update(nxt)

        feed = _as_feed_dict(feed)
        fetch_names = [
            f.name if hasattr(f, "name") else str(f) for f in fetch_list
        ]
        if mesh is None:
            # zero-code-change entry: PADDLE_TPU_MESH selects the GSPMD
            # path for every plain run (startup programs included —
            # their state lands pre-sharded per the same rules)
            from paddle_tpu.parallel.mesh import mesh_from_flag

            mesh = mesh_from_flag()
        return self.engine.run_block(
            program.desc,
            0,
            scope,
            feed=feed,
            fetch_list=fetch_names,
            is_test=getattr(program, "_is_test", False),
            return_numpy=return_numpy,
            seed=getattr(program, "random_seed", 0) or 0,
            amp=getattr(program, "_amp", False),
            accumulate_steps=accumulate_steps,
            remat_segments=remat_segments,
            verify=verify,
            opt_level=opt_level,
            mesh=mesh,
            shard_rules=shard_rules,
            data_axes=tuple(data_axes),
            dispatch_steps=dispatch_steps,
        )
