"""Rollback-on-fault step driver: ``Executor.run`` + an attached
``CheckpointManager`` composed into a training loop that survives
numeric blow-ups and injected step faults (SURVEY §5: the reference's
production story is checkpoint-based recovery around Fluid's
save/load-persistables machinery; TensorFlow likewise treats
checkpoint/restore fault tolerance as a whole-system requirement —
PAPERS.md).

The loop contract: batches come from a ``batch_fn(step)`` callable so
the driver can REWIND — after a fault it restores the last complete
checkpoint and replays the same batches from there, which (for
deterministic programs; dropout re-draws per engine run counter) lands
the run on the identical trajectory an uninterrupted run produces.
Every recovery is recorded as ``recovery.*`` observability
counters/events, so a telemetry sink from a chaotic run reads as an
incident log.
"""

import numpy as np

from paddle_tpu import observability as obs
from paddle_tpu.resilience.faultinject import (InjectedFault,
                                               PREEMPT_EXIT_CODE,
                                               fault_point)
from paddle_tpu.resilience.sentinel import SDCBlamed, SDCSuspect

__all__ = ["FaultBudgetExceeded", "ResilientDriver"]


class FaultBudgetExceeded(RuntimeError):
    """More rollbacks than ``max_rollbacks`` — the fault is persistent
    (every replay re-trips), not transient; chains the last trip."""


def _is_recoverable(exc):
    """Step failures the rollback path owns: injected faults and the
    engine's nan/inf guard trip. Anything else (user bugs, OOM, shape
    errors) propagates — rolling back cannot fix a deterministic
    crash and would just burn the fault budget re-proving it."""
    if isinstance(exc, InjectedFault):
        return True
    return isinstance(exc, RuntimeError) and "check_nan_inf" in str(exc)


class ResilientDriver:
    """Checkpointed training loop with rollback-on-fault.

    ::

        mgr = CheckpointManager(root)
        drv = ResilientDriver(exe, main, [loss], mgr, scope=scope,
                              ckpt_interval=10)
        losses = drv.train(batch_fn, n_steps=200)

    Behaviour per fault (nan/inf trip or injected step fault):

    0. the executor's async dispatch window (``dispatch_steps>1``) is
       DISCARDED — in-flight steps will be replayed from the
       checkpoint, so their stale deferred fetches/verdicts must not
       resolve or re-raise (a deferred ``check_nan_inf`` trip names
       its original step and rolls back exactly like a synchronous
       one; the driver also drains the window before every checkpoint
       save so a poisoned in-flight step can never be published);
    1. the in-flight async save (if any) is joined — never restore
       under a half-written checkpoint;
    2. state rolls back to the latest COMPLETE checkpoint
       (``io.load_checkpoint``) and the step counter rewinds to it;
    3. with ``skip_poison_batch=True`` the failing step's batch is
       excluded from the replay (the poison-pill escape hatch for
       data-dependent blow-ups; off by default because dropping data
       changes the trajectory);
    4. ``recovery.rollback`` counter + event record it.

    ``max_rollbacks`` bounds total recoveries; a run needing more is
    systematically sick and fails with ``FaultBudgetExceeded``.

    Resume: when the manager's root already holds a checkpoint (the
    supervised launcher re-spawned this worker after a gang failure,
    pointing ``PADDLE_TPU_RECOVERY_CKPT`` at the same root), ``train``
    restores it and continues from that step instead of step 0 —
    callers run the startup program unconditionally and let the
    restore overwrite.
    """

    def __init__(self, executor, program, fetch_list, manager, scope=None,
                 ckpt_interval=10, max_rollbacks=8, skip_poison_batch=False,
                 check_nan_inf=True):
        from paddle_tpu.executor import global_scope

        self.exe = executor
        self.program = program
        self.fetch_list = list(fetch_list)
        self.manager = manager
        self.scope = scope if scope is not None else global_scope()
        self.ckpt_interval = int(ckpt_interval)
        self.max_rollbacks = int(max_rollbacks)
        self.skip_poison_batch = bool(skip_poison_batch)
        self.rollbacks = 0
        # graceful preemption (SIGTERM or the `preempt` fault point):
        # the loop checks this at the step seam, drains + checkpoints,
        # then exits PREEMPT_EXIT_CODE
        self._preempted = False
        self._sigterm_installed = False
        self._old_sigterm = None
        # engine run-counter -> driver batch step, recorded BEFORE each
        # run: an SDCSuspect names the engine step that computed the bad
        # digest (possibly several window slots back); the driver
        # answers in batch steps
        self._engine_steps = {}
        if check_nan_inf:
            # the guard IS the fault detector for numeric blow-ups; the
            # driver is pointless without one, so it defaults on here
            # even when the global flag is down
            executor.engine.check_nan_inf = True

    # -- checkpointing -----------------------------------------------------
    def _save(self, step, blocking=False):
        from paddle_tpu import io

        io.save_checkpoint_async(self.manager, step,
                                 main_program=self.program,
                                 scope=self.scope, blocking=blocking)
        obs.inc("recovery.ckpt_saved")
        # the critical path the step loop actually waited on: the host
        # snapshot (async) or the full write (blocking). The drain
        # before a save already marked host_sync, so this charge is the
        # save alone.
        obs.goodput.mark("ckpt_critical")

    def resume_step(self):
        """The step a fresh ``train`` would resume from (latest complete
        checkpoint), or None when the root holds none."""
        return self.manager.latest_step()

    def _drain(self):
        """Barrier the executor's async dispatch window (a no-op at
        dispatch depth 1). Deferred ``check_nan_inf`` verdicts from
        in-flight steps raise HERE, naming their original step — the
        driver drains before every checkpoint save so a poisoned
        in-flight step can never be published as a 'good' checkpoint
        (which would become the rollback target and trap the run)."""
        sync = getattr(self.exe, "sync", None)
        if sync is not None:
            sync()

    def _poison_ckpt_root(self, step):
        """Destroy the manager's LOCAL checkpoint root in place (the
        ``disk_fail`` fault point's corruption): join the writer first
        so no save races the rmtree, then wipe. With
        ``PADDLE_TPU_CKPT_REPLICAS`` > 0 the manager's quorum restore
        path recovers every later restore from a peer root's replica."""
        import os
        import shutil

        self.manager.wait()
        shutil.rmtree(self.manager.root, ignore_errors=True)
        os.makedirs(self.manager.root, exist_ok=True)
        obs.inc("recovery.disk_poisoned")
        obs.event("ckpt.root_poisoned", step=step, root=self.manager.root)

    def _rollback(self, failed_step, exc):
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            raise FaultBudgetExceeded(
                "%d rollbacks exceed the budget of %d (last fault at "
                "step %d)" % (self.rollbacks, self.max_rollbacks,
                              failed_step)) from exc
        # drop the in-flight dispatch window first: its steps are about
        # to be replayed from the checkpoint, and their stale deferred
        # verdicts/fetches must neither re-raise nor resolve
        engine = getattr(self.exe, "engine", None)
        if engine is not None and hasattr(engine, "discard_window"):
            engine.discard_window()
        # join the in-flight save next: it predates the fault (saves
        # happen on good steps) but restoring mid-write would race it
        self.manager.wait()
        try:
            self.manager.check_error()
        except RuntimeError:
            # a failed BACKGROUND save must not mask the recovery — the
            # older complete checkpoint is still the rollback target
            obs.inc("recovery.ckpt_save_failed")
        from paddle_tpu import io

        step = io.load_checkpoint(self.manager, main_program=self.program,
                                  scope=self.scope)
        obs.inc("recovery.rollback")
        obs.event("recovery.rollback", failed_step=failed_step,
                  restored_step=step, reason=str(exc)[:200])
        obs.reqtrace.step_event("rollback", failed_step,
                                restored_step=step)
        # window discard + writer join + restore: all wall the fault
        # cost, charged with the steps about to be replayed
        obs.goodput.mark("rollback_replay")
        return step

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        """Join the async checkpoint writer and SURFACE any error it
        recorded. Without this, a process that exits right after a
        ``save(blocking=False)`` silently loses the writer's failure —
        the caller believes the final state is durable when it is not.
        Call it (or use the driver as a context manager) after the last
        ``train``."""
        self.manager.wait()
        self.manager.check_error()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        else:
            # still join the writer (no orphaned thread), but never mask
            # the active exception with a background-save error
            try:
                self.manager.wait()
            except Exception:
                pass
        return False

    # -- graceful preemption ----------------------------------------------
    def _install_sigterm(self):
        """SIGTERM -> finish the in-flight work, checkpoint, exit
        PREEMPT_EXIT_CODE. Main-thread only (signal module contract);
        worker threads skip the handler and keep the fault-point path."""
        import signal

        if self._sigterm_installed:
            return
        try:
            self._old_sigterm = signal.signal(
                signal.SIGTERM,
                lambda signum, frame: setattr(self, "_preempted", True))
            self._sigterm_installed = True
        except ValueError:
            self._sigterm_installed = False

    def _restore_sigterm(self):
        import signal

        if self._sigterm_installed:
            signal.signal(signal.SIGTERM, self._old_sigterm)
            self._sigterm_installed = False
            self._old_sigterm = None

    def _graceful_exit(self, step):
        """The preemption protocol: drain the dispatch window so every
        enqueued step retires (or the window is discarded if one was
        poisoned), take a BLOCKING checkpoint, flush telemetry, exit
        with the code the supervisor restarts without budget."""
        obs.inc("recovery.preempted")
        # the whole eviction protocol — drain (host_sync at retire) and
        # blocking save (ckpt_critical) — is preemption cost: the
        # eviction chose the timing, so every inner charge lands in
        # preempt_drain
        with obs.goodput.redirected({"host_sync": "preempt_drain",
                                     "ckpt_critical": "preempt_drain",
                                     "compute": "preempt_drain"}):
            try:
                self._drain()
                self._save(step, blocking=True)
            except Exception:
                # a fault surfaced while draining: do not publish that
                # state — the latest complete checkpoint is durable
                engine = getattr(self.exe, "engine", None)
                if engine is not None and hasattr(engine,
                                                  "discard_window"):
                    engine.discard_window()
                try:
                    self.manager.wait()
                except Exception:
                    pass
        obs.event("recovery.preempted", step=step)
        obs.goodput.mark("preempt_drain")
        obs.goodput.publish()
        try:
            obs.flush_sink()
        except Exception:
            pass
        raise SystemExit(PREEMPT_EXIT_CODE)

    # -- SDC recovery ------------------------------------------------------
    def _can_quarantine(self, dev):
        """In-process quarantine needs an elastic mesh (`dp=-1`) with a
        survivor left after removing ``dev``; otherwise the blame
        propagates as SDCBlamed (gang-level shrink or hard failure)."""
        if dev is None:
            return False
        from paddle_tpu import flags

        if "-1" not in str(flags.get_flag("mesh")):
            return False
        from paddle_tpu.resilience import elastic

        surviving = elastic.surviving_devices()
        return (len(surviving) > 1
                and any(int(d.id) == int(dev) for d in surviving))

    def _sdc_recover(self, exc, results, on_step):
        """Route an SDCSuspect through the sentinel's replay vote:
        transient/genuine re-deliver the verified step and continue from
        the step after it; blamed quarantines the device (elastic mesh)
        or raises SDCBlamed; a missing replay record degrades to the
        classic checkpoint rollback. Returns the next batch step."""
        engine = getattr(self.exe, "engine", None)
        b = self._engine_steps.get(exc.step)
        obs.inc("recovery.sdc_suspects")
        try:
            verdict = engine.sdc_recover(exc.step,
                                         reason=getattr(exc, "reason", None))
        except Exception:
            # replay record evicted (window deeper than sdc_retain) or
            # the replay itself failed: the checkpoint path still works
            obs.inc("recovery.sdc_replay_unavailable")
            return self._rollback(exc.step if b is None else b, exc)
        # the window holds steps enqueued AFTER the suspect — they ran
        # on unverified state and will be re-run; their records and the
        # sentinel's now-stale retained inputs are dropped together
        if engine is not None and hasattr(engine, "discard_window"):
            engine.discard_window()
        if verdict["kind"] == "blamed":
            dev = verdict.get("device")
            failed = exc.step if b is None else b
            if self._can_quarantine(dev):
                from paddle_tpu.resilience import elastic

                elastic.mark_device_lost(dev)
                obs.inc("recovery.sdc_quarantine")
                obs.event("recovery.sdc_quarantine", device=int(dev),
                          step=failed)
                # restore + replay: the next run re-plans `dp=-1` over
                # the survivors and reshards (elastic's shrink path)
                return self._rollback(failed, exc)
            raise SDCBlamed(exc.step, dev) from exc
        if b is None:
            # engine step unmapped (another program ran in between):
            # the state was verified and adopted, but WHICH batch to
            # re-deliver is unknown — rollback keeps the trajectory
            return self._rollback(exc.step, exc)
        results[b] = verdict["fetches"]
        if on_step is not None:
            on_step(b, verdict["fetches"])
        obs.event("recovery.sdc_%s" % verdict["kind"], step=b)
        return b + 1

    # -- the loop ----------------------------------------------------------
    def train(self, batch_fn, n_steps, start_step=None, on_step=None):
        """Run steps ``[start, n_steps)``; returns the per-step fetch
        lists in step order (skipped poison batches are absent).

        ``batch_fn(step) -> feed dict`` must be deterministic in
        ``step`` — it is re-invoked for replayed steps after a
        rollback and for the resumed range after a gang restart.

        ``on_step(step, fetches)`` fires after each SUCCESSFUL step
        (replays included, re-firing for the replayed steps; failed
        steps never fire). A worker that may be killed and respawned
        streams its per-step results to durable storage here — the
        in-memory return value dies with the process.

        While ``train`` runs, SIGTERM means graceful preemption: the
        window drains, a blocking checkpoint publishes, and the process
        exits ``PREEMPT_EXIT_CODE`` (46) — which the supervisor restarts
        without spending restart budget. The previous handler is
        restored on return."""
        self._install_sigterm()
        # cross-process trace adoption: under a tracing supervisor the
        # incarnation joins the job trace exported via
        # PADDLE_TPU_TRACE_ID — eager spans (a killed incarnation's
        # half of the trace must already be on disk), fenced by the
        # incarnation number exactly like heartbeats. The context is
        # activated thread-locally so the engine's dispatch-window
        # enqueue/retire seams emit into the same trace.
        tctx = obs.reqtrace.adopt_env()
        if tctx is not None:
            obs.reqtrace.span_event(tctx, "train_start",
                                    obs.reqtrace.now_us(), 0.0,
                                    n_steps=n_steps)
        try:
            return self._train_impl(batch_fn, n_steps, start_step, on_step)
        finally:
            if tctx is not None:
                obs.reqtrace.deactivate()
            self._restore_sigterm()
            if obs.goodput.enabled():
                # final ledger state must reach the sink: a worker that
                # never detaches (killed next incarnation, or just
                # exits) would otherwise leave only mid-compile snaps
                # behind and perf_report --goodput would see no gauges
                obs.goodput.publish()
                obs.flush_sink(snap=True)

    def _train_impl(self, batch_fn, n_steps, start_step, on_step):
        if start_step is None:
            start_step = self.resume_step()
            if start_step is not None:
                from paddle_tpu import io

                # anchor the ledger before the restore so the resume
                # wall (the worker-side tail of a restart) is charged,
                # not silently excluded by the lazy first-mark anchor
                obs.goodput.mark("idle")
                io.load_checkpoint(self.manager,
                                   main_program=self.program,
                                   scope=self.scope, step=start_step)
                obs.inc("recovery.resume")
                obs.event("recovery.resume", step=start_step)
                obs.reqtrace.step_event("resume", start_step)
                obs.goodput.mark("restart_downtime")
            else:
                start_step = 0
        if start_step == 0:
            # the step-0 baseline: the earliest fault must have a
            # rollback target (blocking — it must exist before step 1)
            self._save(0, blocking=True)
        results = {}
        skip = set()
        step = start_step
        # highest step ever reached this process: a step below it is a
        # REPLAY after a rollback — its wall is re-earned, not new
        # progress, so the ledger books it as rollback_replay
        high_water = start_step
        while True:
            if step >= n_steps:
                # drain the dispatch window before the final save: a
                # deferred fault from an in-flight step rolls back and
                # re-enters the loop like any step fault
                try:
                    self._drain()
                except SDCSuspect as e:
                    step = self._sdc_recover(e, results, on_step)
                    continue
                except Exception as e:  # noqa: BLE001 - filtered below
                    if not _is_recoverable(e):
                        raise
                    step = self._rollback(step, e)
                    continue
                break
            # worker-liveness fault points: a supervised-launcher test
            # kills (or wedges, for the heartbeat watchdog) this process
            # here, between steps — the preemption seam (never
            # mid-device-step in real life either)
            fault_point("worker_kill", step=step)
            fault_point("worker_hang", step=step)
            fault_point("worker_loss", step=step)
            if fault_point("preempt", step=step):
                # poison-style: the driver owns the graceful-exit
                # protocol, identical to a real SIGTERM arriving here
                self._preempted = True
            if self._preempted:
                self._graceful_exit(step)
            if fault_point("disk_fail", step=step):
                # poison-style: the driver owns the checkpoint root, so
                # IT destroys it — the dead-local-disk scenario quorum
                # restore recovers from via a peer root's replica
                self._poison_ckpt_root(step)
            if step in skip:
                obs.inc("recovery.batch_skipped")
                step += 1
                continue
            feed = batch_fn(step)
            engine = getattr(self.exe, "engine", None)
            if engine is not None:
                # prospective: THIS run will be engine step counter+1
                self._engine_steps[engine._run_counter + 1] = step
                if len(self._engine_steps) > 128:
                    for k in sorted(self._engine_steps)[:-64]:
                        del self._engine_steps[k]
            try:
                with obs.goodput.redirected(
                        {"compute": "rollback_replay"}
                        if step < high_water else {}):
                    out = self.exe.run(self.program, feed=feed,
                                       fetch_list=self.fetch_list,
                                       scope=self.scope)
            except SDCSuspect as e:
                step = self._sdc_recover(e, results, on_step)
                continue
            except Exception as e:  # noqa: BLE001 - filtered below
                if not _is_recoverable(e):
                    raise
                # a deferred verdict surfacing on this run names an
                # EARLIER step; skipping THIS batch would drop the
                # wrong one, so the poison-pill escape hatch only
                # applies to synchronously detected faults
                if self.skip_poison_batch and "deferred" not in str(e):
                    skip.add(step)
                step = self._rollback(step, e)
                continue
            results[step] = out
            if on_step is not None:
                on_step(step, out)
            step += 1
            high_water = max(high_water, step)
            if self.ckpt_interval and step % self.ckpt_interval == 0 \
                    and step < n_steps:
                # drain first: every step the checkpoint will cover must
                # have retired (and passed its deferred nan verdict) —
                # publishing a poisoned snapshot would make IT the
                # rollback target and trap the run in a restore loop
                try:
                    self._drain()
                except SDCSuspect as e:
                    step = self._sdc_recover(e, results, on_step)
                    continue
                except Exception as e:  # noqa: BLE001 - filtered below
                    if not _is_recoverable(e):
                        raise
                    step = self._rollback(step, e)
                    continue
                self._save(step)
        # final checkpoint marks completion (and is what a restarted
        # gang member resumes past); blocking so the caller returns
        # with everything durable
        self._save(n_steps, blocking=True)
        return [results[s] for s in sorted(results)]

    # convenience for tests / tools
    def last_values(self, results):
        return [float(np.asarray(r[0]).reshape(-1)[0]) for r in results]
