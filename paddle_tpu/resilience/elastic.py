"""Elastic capacity: act on health verdicts without losing the job.

PR 9 gave the system eyes — hung/dead rank classification, SLO burn-rate
windows behind ``InferenceServer.health()`` — but the only actuator was
"restart the whole gang". This module adds the three actuators the
ROADMAP's elastic-capacity rung names (PAPERS.md: MLPerf pod-scale
practice treats worker loss as routine, arXiv:1909.09756; TensorFlow's
design goal of tolerating worker loss without restarting the world,
arXiv:1605.08695):

* **Lost-device registry** — the training-side shrink seam.
  ``mark_device_lost(id)`` records a device as permanently gone (and
  mirrors the set into ``PADDLE_TPU_LOST_DEVICES`` so respawned workers
  inherit it); ``parallel.mesh.mesh_from_flag`` then re-plans any
  ``dp=-1`` axis over ``surviving_devices()`` only. The engine's
  executable cache keys on ``mesh_signature``, so the shrunk mesh is
  automatically a fresh compile, and the single-process donated-state
  path reshards live arrays onto it (``jax.device_put`` on sharding
  mismatch) — no engine change needed beyond what already exists.

* **LOST_EXIT_CODE / gang shrink** — re-exported from ``faultinject``;
  ``distributed/launch.supervise`` treats a gang failure with this rc
  (or an exhausted restart budget) as permanent and, within
  ``PADDLE_TPU_MAX_SHRINKS``, relaunches the surviving gang one worker
  smaller (``health.mesh_shrunk`` event) — each survivor resumes from
  its last complete checkpoint via the normal recovery path.

* **FleetRouter** — the serving-side actuator: a round-robin router
  over ``InferenceServer`` workers that scales OUT when any worker's
  FAST burn-rate window trips (detection speed: acting before the slow
  window confirms is the point — capacity arrives while the SLO can
  still be saved) and scales IN only once every worker's SLOW window
  has recovered (confirmation: a brief lull does not shed capacity),
  with a cooldown between actions and hard min/max bounds
  (``PADDLE_TPU_FLEET_MIN_WORKERS`` / ``_MAX_WORKERS`` /
  ``_COOLDOWN_S``). Requests route to live, non-burning workers first.

Every decision is observable: ``fleet.scale_out`` / ``fleet.scale_in``
counters, ``health.fleet_scaled`` events, ``fleet.spawn_ms`` timing.

Request protection (all knobs default off — ``submit`` then routes
exactly as before): with ``PADDLE_TPU_SUBMIT_RETRIES`` > 0 a request
whose worker fails (dead at pick time, rejecting at admission, or
erroring mid-flight) is relaunched on another live worker under the
SAME trace id, each relaunch stamped as a ``trace.retry`` span in the
stitched trace; ``PADDLE_TPU_HEDGE_AFTER_MS`` speculatively re-issues
stragglers to a second worker (first result wins, loser cancelled);
``PADDLE_TPU_FLEET_BREAKER_FAILURES`` arms a per-worker circuit
breaker (inference/admission.CircuitBreaker) that takes a
consecutively-failing worker out of rotation and re-admits it through
a single half-open probe after ``PADDLE_TPU_FLEET_BREAKER_RESET_S``.
Counters: ``fleet.retry`` / ``fleet.hedge`` / ``fleet.hedge_win`` /
``fleet.breaker_trips``; breaker flips emit ``health.breaker_open`` /
``health.breaker_closed`` events.
"""

import threading
import time
from concurrent.futures import Future

from paddle_tpu import flags
from paddle_tpu.resilience.faultinject import LOST_EXIT_CODE  # noqa: F401

__all__ = ["LOST_EXIT_CODE", "FleetRouter", "lost_device_ids",
           "mark_device_lost", "reset_lost", "surviving_devices"]


# --- lost-device registry --------------------------------------------------
# In-process marks union with the PADDLE_TPU_LOST_DEVICES flag (which
# set_flags mirrors into the environment, so a supervisor's verdict
# reaches respawned workers for free).

_lost_lock = threading.Lock()
_lost = set()


def _flag_lost():
    raw = flags.get_flag("lost_devices")
    out = set()
    for part in str(raw).split(","):
        part = part.strip()
        if part:
            out.add(int(part))
    return out


def lost_device_ids():
    """The set of device ids currently considered permanently lost:
    in-process marks plus the PADDLE_TPU_LOST_DEVICES flag."""
    with _lost_lock:
        return _lost | _flag_lost()


def mark_device_lost(device):
    """Record ``device`` (a jax device or an int id) as permanently
    lost and mirror the full set into the flag/env so subprocesses and
    later ``mesh_from_flag`` calls re-plan without it."""
    dev_id = int(getattr(device, "id", device))
    with _lost_lock:
        _lost.add(dev_id)
        all_lost = _lost | _flag_lost()
    flags.set_flags(
        {"lost_devices": ",".join(str(i) for i in sorted(all_lost))})
    from paddle_tpu import observability as obs

    obs.inc("elastic.device_lost")
    obs.event("elastic.device_lost", device=dev_id,
              lost=sorted(all_lost))
    return dev_id


def reset_lost():
    """Forget every lost-device mark (test isolation)."""
    with _lost_lock:
        _lost.clear()
    flags.reset_flag("lost_devices")


def surviving_devices():
    """``jax.devices()`` minus the lost set — the device pool a
    ``dp=-1`` mesh axis re-plans over."""
    import jax

    lost = lost_device_ids()
    if not lost:
        return list(jax.devices())
    return [d for d in jax.devices() if int(d.id) not in lost]


# --- serving fleet ---------------------------------------------------------
class FleetRouter:
    """SLO-driven autoscaler + round-robin router over InferenceServer
    workers.

    ``factory(index) -> worker`` builds one worker (typically an
    ``InferenceServer`` wrapping the shared frozen program; the factory
    owns warmup so a scaled-out worker arrives pre-compiled). The
    router ``start()``s it and routes ``submit()`` calls round-robin
    over live workers, preferring ones whose SLO monitor is not
    burning; with every worker burning it still routes (degraded beats
    dropped).

    Scaling policy (``maybe_scale``, one decision per call — drive it
    from the poll thread via ``start(poll_interval_s=...)`` or directly
    with a synthetic clock in tests):

    * scale OUT when any worker's FAST burn window trips
      (``InferenceServer.fast_burning``), the fleet is below
      ``max_workers``, and the cooldown has passed — the fast window is
      the detection signal, so capacity arrives BEFORE the slow window
      would confirm a page;
    * scale IN when no fast window is burning, EVERY worker's SLOW
      window has recovered (``InferenceServer.slow_recovered``), the
      fleet is above ``min_workers``, and the cooldown has passed —
      the newest worker is drained (``stop()`` resolves its queue) and
      retired;
    * the cooldown between any two actions is the hysteresis that
      keeps a threshold-flapping burn from thrashing the fleet.
    """

    def __init__(self, factory, min_workers=None, max_workers=None,
                 cooldown_s=None, clock=time.monotonic, retries=None,
                 hedge_after_ms=None, breaker_failures=None,
                 breaker_reset_s=None):
        self.factory = factory
        self.min_workers = (int(flags.get_flag("fleet_min_workers"))
                            if min_workers is None else int(min_workers))
        self.max_workers = (int(flags.get_flag("fleet_max_workers"))
                            if max_workers is None else int(max_workers))
        if self.min_workers < 1:
            raise ValueError("fleet min_workers must be >= 1")
        if self.max_workers < self.min_workers:
            raise ValueError(
                "fleet max_workers (%d) < min_workers (%d)"
                % (self.max_workers, self.min_workers))
        self.cooldown_s = (float(flags.get_flag("fleet_cooldown_s"))
                           if cooldown_s is None else float(cooldown_s))
        self.clock = clock
        self.workers = []
        self.scale_outs = 0
        self.scale_ins = 0
        self.last_spawn_ms = None
        #: burn snapshot of the worker that triggered the latest
        #: scale-out — proves the decision fired on the FAST window
        #: while the slow window was still quiet (tools/serve_probe.py
        #: --autoscale asserts exactly this)
        self.last_scale_out_burn = None
        # request-protection envelope (all default 0/off -> the
        # unprotected fast path, byte-identical routing to HEAD)
        self.submit_retries = (int(flags.get_flag("submit_retries"))
                               if retries is None else int(retries))
        self.hedge_after_ms = (float(flags.get_flag("hedge_after_ms"))
                               if hedge_after_ms is None
                               else float(hedge_after_ms))
        self.breaker_failures = (
            int(flags.get_flag("fleet_breaker_failures"))
            if breaker_failures is None else int(breaker_failures))
        self.breaker_reset_s = (
            float(flags.get_flag("fleet_breaker_reset_s"))
            if breaker_reset_s is None else float(breaker_reset_s))
        self.retries = 0        # relaunches actually performed
        self.hedges = 0
        self.hedge_wins = 0
        self._breakers = {}     # id(worker) -> CircuitBreaker
        self._lock = threading.Lock()
        self._rr = 0
        self._spawned = 0
        self._last_scale = None
        self._poll = None
        self._stopping = False

    # -- lifecycle -------------------------------------------------------
    def start(self, poll_interval_s=None):
        """Spawn up to ``min_workers`` and optionally a daemon poll
        thread calling ``maybe_scale`` every ``poll_interval_s``."""
        while self.n_workers < self.min_workers:
            self._add(self._build_worker())
        if poll_interval_s:
            self._stopping = False
            self._poll = threading.Thread(
                target=self._poll_loop, args=(float(poll_interval_s),),
                name="paddle-tpu-fleet", daemon=True)
            self._poll.start()
        return self

    def stop(self):
        """Stop the poll thread and drain + stop every worker (each
        worker's ``stop()`` resolves its queued futures first)."""
        self._stopping = True
        if self._poll is not None:
            self._poll.join()
            self._poll = None
        with self._lock:
            workers, self.workers = list(self.workers), []
            self._breakers.clear()
        for w in workers:
            w.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _poll_loop(self, interval_s):
        while not self._stopping:
            try:
                self.maybe_scale()
            except Exception:   # a sick worker probe must not kill scaling
                pass
            time.sleep(interval_s)

    def _build_worker(self):
        """Build + start one worker OUTSIDE the router lock — a model
        build takes seconds, and in-flight ``submit`` calls must keep
        routing to the existing fleet while the new capacity warms."""
        from paddle_tpu import observability as obs

        with self._lock:
            idx = self._spawned
            self._spawned += 1
        t0 = time.perf_counter()
        w = self.factory(idx)
        start = getattr(w, "start", None)
        if start is not None:
            start()                      # idempotent on InferenceServer
        self.last_spawn_ms = (time.perf_counter() - t0) * 1000.0
        obs.observe("fleet.spawn_ms", self.last_spawn_ms)
        return w

    def _add(self, w):
        from paddle_tpu import observability as obs

        with self._lock:
            self.workers.append(w)
            n = len(self.workers)
        obs.set_gauge("fleet.workers", n)
        return n

    # -- routing ---------------------------------------------------------
    @property
    def n_workers(self):
        with self._lock:
            return len(self.workers)

    def submit(self, feed, trace_id=None, deadline_ms=None, priority=0):
        """Route one request; returns the worker's Future.

        With request tracing enabled the router is where the trace ID
        is born (or adopted from the caller): the chosen worker's
        ``submit(feed, trace_id=...)`` joins the same trace, and once
        the worker has opened its span buffer the routing decision
        lands in it as a ``route`` span — a degraded-fleet request
        shows WHICH worker it was pinned to.

        ``deadline_ms`` / ``priority`` forward to the worker's
        admission gate. With any protection knob armed (retry budget,
        hedging, breaker) the returned future is the router's own:
        failed attempts are relaunched on other live workers under the
        same trace id, stragglers are optionally hedged, and the first
        result wins."""
        from paddle_tpu import observability as obs

        rt = obs.reqtrace
        if rt.enabled():
            trace_id = trace_id or rt.new_trace_id()
        if (self.submit_retries > 0 or self.hedge_after_ms > 0
                or self.breaker_failures > 0):
            return _GuardedSubmit(self, feed, trace_id, deadline_ms,
                                  priority).start()
        if not rt.enabled():
            return self._worker_submit(self._pick(), feed, trace_id,
                                       deadline_ms, priority)
        t0_us = rt.now_us()
        w = self._pick()
        fut = self._worker_submit(w, feed, trace_id, deadline_ms,
                                  priority)
        rt.add_span_by_id(trace_id, "route", t0_us,
                          rt.now_us() - t0_us,
                          worker=self._worker_index(w),
                          fleet=self.n_workers, burning=bool(w.burning()))
        return fut

    @staticmethod
    def _worker_submit(w, feed, trace_id, deadline_ms, priority):
        """Forward one request with only the kwargs the caller actually
        supplied, so duck-typed workers that predate the
        deadline/priority API keep working — and the default call stays
        exactly ``w.submit(feed)``."""
        kw = {}
        if trace_id is not None:
            kw["trace_id"] = trace_id
        if deadline_ms is not None:
            kw["deadline_ms"] = deadline_ms
        if priority:
            kw["priority"] = priority
        return w.submit(feed, **kw)

    def _worker_index(self, w):
        with self._lock:
            try:
                return self.workers.index(w)
            except ValueError:
                return -1

    def _breaker(self, w):
        """The worker's CircuitBreaker, created on first use (None with
        the breaker disabled)."""
        if self.breaker_failures <= 0:
            return None
        from paddle_tpu.inference.admission import CircuitBreaker

        with self._lock:
            br = self._breakers.get(id(w))
            if br is None:
                br = CircuitBreaker(
                    self.breaker_failures, self.breaker_reset_s,
                    name=getattr(w, "name", "worker-%d" % id(w)),
                    clock=self.clock)
                self._breakers[id(w)] = br
        return br

    def _breaker_allows(self, w, now):
        """May the breaker route to this worker? A True answer for a
        half-open breaker CONSUMES the probe token, so only call this
        for a worker that will actually be used on yes."""
        if self.breaker_failures <= 0:
            return True
        with self._lock:
            br = self._breakers.get(id(w))
        return br is None or br.allow(now)

    def _pick(self, exclude=None):
        """Choose a worker: round-robin over live workers, preferring
        (1) not burning + breaker closed, then (2) breaker closed, then
        (3) any live worker — degraded service beats dropping the
        request. ``exclude`` soft-avoids workers a retry already tried
        (ignored when they are the only ones left)."""
        with self._lock:
            workers = list(self.workers)
            self._rr += 1
            offset = self._rr
        if not workers:
            raise RuntimeError("FleetRouter has no workers (start() it)")
        n = len(workers)
        order = [workers[(offset + k) % n] for k in range(n)]
        alive = [w for w in order if w.alive()]
        if not alive:
            raise RuntimeError("FleetRouter: no live workers in a fleet "
                               "of %d" % n)
        if exclude:
            fresh = [w for w in alive if w not in exclude]
            if fresh:
                alive = fresh
        now = self.clock()
        for w in alive:
            if not w.burning() and self._breaker_allows(w, now):
                return w
        for w in alive:
            if self._breaker_allows(w, now):
                return w
        return alive[0]

    # -- scaling ---------------------------------------------------------
    def maybe_scale(self, now=None):
        """One scaling decision; returns +1 (scaled out), -1 (scaled
        in), or 0. ``now`` defaults to the router's clock and is passed
        through to the workers' burn-rate windows so tests can drive a
        synthetic timeline."""
        from paddle_tpu import observability as obs

        now = self.clock() if now is None else now
        with self._lock:
            workers = list(self.workers)
            last = self._last_scale
        if not workers:
            return 0
        in_cooldown = (last is not None
                       and (now - last) < self.cooldown_s)
        fast = [w for w in workers if w.fast_burning(now=now)]
        if fast:
            if in_cooldown or len(workers) >= self.max_workers:
                return 0
            trigger = fast[0]
            snap_fn = getattr(trigger, "burn_snapshot", None)
            self.last_scale_out_burn = snap_fn(now=now) if snap_fn \
                else None
            size = self._add(self._build_worker())
            with self._lock:
                self._last_scale = now
            self.scale_outs += 1
            obs.inc("fleet.scale_out")
            obs.event("health.fleet_scaled", direction="out",
                      workers=size, spawn_ms=round(self.last_spawn_ms
                                                   or 0.0, 1),
                      burn=self.last_scale_out_burn)
            return 1
        if (len(workers) > self.min_workers and not in_cooldown
                and all(w.slow_recovered(now=now) for w in workers)):
            with self._lock:
                if len(self.workers) <= self.min_workers:
                    return 0
                w = self.workers.pop()
                self._breakers.pop(id(w), None)
                size = len(self.workers)
                self._last_scale = now
            w.stop()                     # drains its queue first
            self.scale_ins += 1
            obs.inc("fleet.scale_in")
            obs.set_gauge("fleet.workers", size)
            obs.event("health.fleet_scaled", direction="in",
                      workers=size)
            return -1
        return 0

    def stats(self):
        with self._lock:
            breakers = list(self._breakers.values())
        return {"workers": self.n_workers, "scale_outs": self.scale_outs,
                "scale_ins": self.scale_ins,
                "last_spawn_ms": self.last_spawn_ms,
                "last_scale_out_burn": self.last_scale_out_burn,
                "retries": self.retries, "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
                "breaker_trips": sum(b.trips for b in breakers),
                "breakers_open": sum(1 for b in breakers
                                     if b.state != "closed")}

    def health(self):
        """Fleet-level readiness: per-worker snapshots plus the verdict
        a load balancer wants (any live worker = routable)."""
        with self._lock:
            workers = list(self.workers)
        snaps = [w.health() for w in workers]
        return {"workers": len(workers),
                "healthy": any(s.get("worker_alive") for s in snaps),
                "scale_outs": self.scale_outs,
                "scale_ins": self.scale_ins,
                "per_worker": snaps}


class _GuardedSubmit:
    """One routed request under the protection envelope.

    The caller holds ONE outer future; underneath it the guard launches
    worker attempts — the primary, bounded retries after failures, and
    at most one hedge for a straggler. First successful attempt wins
    the outer future and cancels the losers; the outer future fails
    only once no attempt is left in flight and the retry budget is
    spent (or the failure is a ``DeadlineExceeded``, which no other
    worker can outrun — the deadline is global).

    Trace stitching: every attempt submits under the SAME trace id, so
    a retried request's spans from both workers land in one trace; the
    ``trace.retry`` / ``trace.hedge`` span is added AFTER the relaunch
    has re-opened the span buffer (the failed attempt's ``finish``
    closed it), which is what makes the stitched timeline show the
    hand-off."""

    def __init__(self, router, feed, trace_id, deadline_ms, priority):
        self.router = router
        self.feed = feed
        self.trace_id = trace_id
        self.deadline_ms = deadline_ms
        self.priority = priority
        self.outer = Future()
        self.outer.trace_id = trace_id
        self.outer.t_enq = time.monotonic()
        self.outer.t_done = None
        self._lock = threading.Lock()
        self._tried = []        # workers any attempt has been sent to
        self._inflight = []     # inner futures not yet resolved
        self._attempts_used = 0  # retry budget consumed
        self._timer = None

    def start(self):
        err = self._attempt(first=True)
        if err is not None:
            self._retry(err)
        if (self.router.hedge_after_ms > 0 and not self.outer.done()):
            self._timer = threading.Timer(
                self.router.hedge_after_ms / 1000.0, self._hedge)
            self._timer.daemon = True
            self._timer.start()
        return self.outer

    # -- attempts --------------------------------------------------------
    def _attempt(self, first=False, hedge=False, worker=None):
        """Send the request to one worker. Returns None when an attempt
        is in flight (or already resolved), else the synchronous error
        (nothing was launched)."""
        r = self.router
        if worker is None:
            try:
                worker = r._pick(exclude=None if first else self._tried)
            except RuntimeError as e:
                return e
        self._tried.append(worker)
        try:
            inner = r._worker_submit(worker, self.feed, self.trace_id,
                                     self.deadline_ms, self.priority)
        except Exception as e:  # dead worker, Rejected, ...
            br = r._breaker(worker)
            if br is not None:
                br.record_failure()
            return e
        self._note_route(worker, hedge)
        if not hasattr(inner, "add_done_callback"):
            # duck-typed worker answered synchronously with a value
            self._resolve_ok(worker, inner, hedge)
            return None
        with self._lock:
            self._inflight.append(inner)
        inner.add_done_callback(
            lambda f, w=worker, h=hedge: self._done(w, f, h))
        return None

    def _retry(self, exc):
        """Consume one retry and relaunch; fails the outer future with
        ``exc`` once the budget is spent or retrying cannot help."""
        from paddle_tpu.inference.admission import DeadlineExceeded

        r = self.router
        if isinstance(exc, DeadlineExceeded):
            self._maybe_fail(exc)
            return
        with self._lock:
            if self._attempts_used >= r.submit_retries:
                spent = True
            else:
                spent = False
                self._attempts_used += 1
                attempt = self._attempts_used
        if spent:
            self._maybe_fail(exc)
            return
        r.retries += 1
        from paddle_tpu import observability as obs

        obs.inc("fleet.retry")
        err = self._attempt()
        if err is None:
            # the relaunch re-opened the trace buffer — the retry span
            # lands inside the stitched trace
            self._span("retry", attempt=attempt, error=repr(exc)[:120])
        else:
            self._retry(err)  # recursion bounded by the retry budget

    def _hedge(self):
        """Timer body: speculatively re-issue a straggler on a second
        worker (skipped when no distinct live worker exists)."""
        r = self.router
        if self.outer.done():
            return
        try:
            w = r._pick(exclude=self._tried)
        except RuntimeError:
            return
        if w in self._tried:
            return              # the straggler is the only worker left
        r.hedges += 1
        from paddle_tpu import observability as obs

        obs.inc("fleet.hedge")
        if self._attempt(hedge=True, worker=w) is None:
            self._span("hedge", worker=r._worker_index(w))

    # -- resolution ------------------------------------------------------
    def _done(self, worker, fut, hedge):
        r = self.router
        with self._lock:
            if fut in self._inflight:
                self._inflight.remove(fut)
        if fut.cancelled():
            return              # a loser we cancelled ourselves
        exc = fut.exception()
        br = r._breaker(worker)
        if br is not None:
            if exc is None:
                br.record_success()
            else:
                br.record_failure()
        if exc is None:
            self._resolve_ok(worker, fut.result(), hedge)
        elif not self.outer.done():
            self._retry(exc)

    def _resolve_ok(self, worker, value, hedge):
        try:
            self.outer.t_done = time.monotonic()
            self.outer.set_result(value)
        except Exception:
            return              # another attempt won the race
        if hedge:
            self.router.hedge_wins += 1
            from paddle_tpu import observability as obs

            obs.inc("fleet.hedge_win")
        if self._timer is not None:
            self._timer.cancel()
        self._cancel_losers()

    def _maybe_fail(self, exc):
        """Fail the outer future — unless another attempt is still in
        flight (it may yet win)."""
        with self._lock:
            if self._inflight:
                return
        if not self.outer.done():
            try:
                self.outer.t_done = time.monotonic()
                self.outer.set_exception(exc)
            except Exception:
                pass
        if self._timer is not None:
            self._timer.cancel()

    def _cancel_losers(self):
        with self._lock:
            losers = list(self._inflight)
        for f in losers:
            try:
                f.cancel()
            except Exception:
                pass

    # -- telemetry -------------------------------------------------------
    def _note_route(self, worker, hedge):
        from paddle_tpu import observability as obs

        rt = obs.reqtrace
        if self.trace_id is None or not rt.enabled():
            return
        r = self.router
        args = {"worker": r._worker_index(worker),
                "fleet": r.n_workers,
                "burning": bool(worker.burning())}
        if hedge:
            args["hedge"] = True
        rt.add_span_by_id(self.trace_id, "route", rt.now_us(), 0.0,
                          **args)

    def _span(self, phase, **args):
        from paddle_tpu import observability as obs

        rt = obs.reqtrace
        if self.trace_id is None or not rt.enabled():
            return
        rt.add_span_by_id(self.trace_id, phase, rt.now_us(), 0.0,
                          **{k: v for k, v in args.items()
                             if v is not None})
