"""Elastic capacity: act on health verdicts without losing the job.

PR 9 gave the system eyes — hung/dead rank classification, SLO burn-rate
windows behind ``InferenceServer.health()`` — but the only actuator was
"restart the whole gang". This module adds the three actuators the
ROADMAP's elastic-capacity rung names (PAPERS.md: MLPerf pod-scale
practice treats worker loss as routine, arXiv:1909.09756; TensorFlow's
design goal of tolerating worker loss without restarting the world,
arXiv:1605.08695):

* **Lost-device registry** — the training-side shrink seam.
  ``mark_device_lost(id)`` records a device as permanently gone (and
  mirrors the set into ``PADDLE_TPU_LOST_DEVICES`` so respawned workers
  inherit it); ``parallel.mesh.mesh_from_flag`` then re-plans any
  ``dp=-1`` axis over ``surviving_devices()`` only. The engine's
  executable cache keys on ``mesh_signature``, so the shrunk mesh is
  automatically a fresh compile, and the single-process donated-state
  path reshards live arrays onto it (``jax.device_put`` on sharding
  mismatch) — no engine change needed beyond what already exists.

* **LOST_EXIT_CODE / gang shrink** — re-exported from ``faultinject``;
  ``distributed/launch.supervise`` treats a gang failure with this rc
  (or an exhausted restart budget) as permanent and, within
  ``PADDLE_TPU_MAX_SHRINKS``, relaunches the surviving gang one worker
  smaller (``health.mesh_shrunk`` event) — each survivor resumes from
  its last complete checkpoint via the normal recovery path.

* **FleetRouter** — the serving-side actuator: a round-robin router
  over ``InferenceServer`` workers that scales OUT when any worker's
  FAST burn-rate window trips (detection speed: acting before the slow
  window confirms is the point — capacity arrives while the SLO can
  still be saved) and scales IN only once every worker's SLOW window
  has recovered (confirmation: a brief lull does not shed capacity),
  with a cooldown between actions and hard min/max bounds
  (``PADDLE_TPU_FLEET_MIN_WORKERS`` / ``_MAX_WORKERS`` /
  ``_COOLDOWN_S``). Requests route to live, non-burning workers first.

Every decision is observable: ``fleet.scale_out`` / ``fleet.scale_in``
counters, ``health.fleet_scaled`` events, ``fleet.spawn_ms`` timing.
"""

import threading
import time

from paddle_tpu import flags
from paddle_tpu.resilience.faultinject import LOST_EXIT_CODE  # noqa: F401

__all__ = ["LOST_EXIT_CODE", "FleetRouter", "lost_device_ids",
           "mark_device_lost", "reset_lost", "surviving_devices"]


# --- lost-device registry --------------------------------------------------
# In-process marks union with the PADDLE_TPU_LOST_DEVICES flag (which
# set_flags mirrors into the environment, so a supervisor's verdict
# reaches respawned workers for free).

_lost_lock = threading.Lock()
_lost = set()


def _flag_lost():
    raw = flags.get_flag("lost_devices")
    out = set()
    for part in str(raw).split(","):
        part = part.strip()
        if part:
            out.add(int(part))
    return out


def lost_device_ids():
    """The set of device ids currently considered permanently lost:
    in-process marks plus the PADDLE_TPU_LOST_DEVICES flag."""
    with _lost_lock:
        return _lost | _flag_lost()


def mark_device_lost(device):
    """Record ``device`` (a jax device or an int id) as permanently
    lost and mirror the full set into the flag/env so subprocesses and
    later ``mesh_from_flag`` calls re-plan without it."""
    dev_id = int(getattr(device, "id", device))
    with _lost_lock:
        _lost.add(dev_id)
        all_lost = _lost | _flag_lost()
    flags.set_flags(
        {"lost_devices": ",".join(str(i) for i in sorted(all_lost))})
    from paddle_tpu import observability as obs

    obs.inc("elastic.device_lost")
    obs.event("elastic.device_lost", device=dev_id,
              lost=sorted(all_lost))
    return dev_id


def reset_lost():
    """Forget every lost-device mark (test isolation)."""
    with _lost_lock:
        _lost.clear()
    flags.reset_flag("lost_devices")


def surviving_devices():
    """``jax.devices()`` minus the lost set — the device pool a
    ``dp=-1`` mesh axis re-plans over."""
    import jax

    lost = lost_device_ids()
    if not lost:
        return list(jax.devices())
    return [d for d in jax.devices() if int(d.id) not in lost]


# --- serving fleet ---------------------------------------------------------
class FleetRouter:
    """SLO-driven autoscaler + round-robin router over InferenceServer
    workers.

    ``factory(index) -> worker`` builds one worker (typically an
    ``InferenceServer`` wrapping the shared frozen program; the factory
    owns warmup so a scaled-out worker arrives pre-compiled). The
    router ``start()``s it and routes ``submit()`` calls round-robin
    over live workers, preferring ones whose SLO monitor is not
    burning; with every worker burning it still routes (degraded beats
    dropped).

    Scaling policy (``maybe_scale``, one decision per call — drive it
    from the poll thread via ``start(poll_interval_s=...)`` or directly
    with a synthetic clock in tests):

    * scale OUT when any worker's FAST burn window trips
      (``InferenceServer.fast_burning``), the fleet is below
      ``max_workers``, and the cooldown has passed — the fast window is
      the detection signal, so capacity arrives BEFORE the slow window
      would confirm a page;
    * scale IN when no fast window is burning, EVERY worker's SLOW
      window has recovered (``InferenceServer.slow_recovered``), the
      fleet is above ``min_workers``, and the cooldown has passed —
      the newest worker is drained (``stop()`` resolves its queue) and
      retired;
    * the cooldown between any two actions is the hysteresis that
      keeps a threshold-flapping burn from thrashing the fleet.
    """

    def __init__(self, factory, min_workers=None, max_workers=None,
                 cooldown_s=None, clock=time.monotonic):
        self.factory = factory
        self.min_workers = (int(flags.get_flag("fleet_min_workers"))
                            if min_workers is None else int(min_workers))
        self.max_workers = (int(flags.get_flag("fleet_max_workers"))
                            if max_workers is None else int(max_workers))
        if self.min_workers < 1:
            raise ValueError("fleet min_workers must be >= 1")
        if self.max_workers < self.min_workers:
            raise ValueError(
                "fleet max_workers (%d) < min_workers (%d)"
                % (self.max_workers, self.min_workers))
        self.cooldown_s = (float(flags.get_flag("fleet_cooldown_s"))
                           if cooldown_s is None else float(cooldown_s))
        self.clock = clock
        self.workers = []
        self.scale_outs = 0
        self.scale_ins = 0
        self.last_spawn_ms = None
        #: burn snapshot of the worker that triggered the latest
        #: scale-out — proves the decision fired on the FAST window
        #: while the slow window was still quiet (tools/serve_probe.py
        #: --autoscale asserts exactly this)
        self.last_scale_out_burn = None
        self._lock = threading.Lock()
        self._rr = 0
        self._spawned = 0
        self._last_scale = None
        self._poll = None
        self._stopping = False

    # -- lifecycle -------------------------------------------------------
    def start(self, poll_interval_s=None):
        """Spawn up to ``min_workers`` and optionally a daemon poll
        thread calling ``maybe_scale`` every ``poll_interval_s``."""
        while self.n_workers < self.min_workers:
            self._add(self._build_worker())
        if poll_interval_s:
            self._stopping = False
            self._poll = threading.Thread(
                target=self._poll_loop, args=(float(poll_interval_s),),
                name="paddle-tpu-fleet", daemon=True)
            self._poll.start()
        return self

    def stop(self):
        """Stop the poll thread and drain + stop every worker (each
        worker's ``stop()`` resolves its queued futures first)."""
        self._stopping = True
        if self._poll is not None:
            self._poll.join()
            self._poll = None
        with self._lock:
            workers, self.workers = list(self.workers), []
        for w in workers:
            w.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _poll_loop(self, interval_s):
        while not self._stopping:
            try:
                self.maybe_scale()
            except Exception:   # a sick worker probe must not kill scaling
                pass
            time.sleep(interval_s)

    def _build_worker(self):
        """Build + start one worker OUTSIDE the router lock — a model
        build takes seconds, and in-flight ``submit`` calls must keep
        routing to the existing fleet while the new capacity warms."""
        from paddle_tpu import observability as obs

        with self._lock:
            idx = self._spawned
            self._spawned += 1
        t0 = time.perf_counter()
        w = self.factory(idx)
        start = getattr(w, "start", None)
        if start is not None:
            start()                      # idempotent on InferenceServer
        self.last_spawn_ms = (time.perf_counter() - t0) * 1000.0
        obs.observe("fleet.spawn_ms", self.last_spawn_ms)
        return w

    def _add(self, w):
        from paddle_tpu import observability as obs

        with self._lock:
            self.workers.append(w)
            n = len(self.workers)
        obs.set_gauge("fleet.workers", n)
        return n

    # -- routing ---------------------------------------------------------
    @property
    def n_workers(self):
        with self._lock:
            return len(self.workers)

    def submit(self, feed, trace_id=None):
        """Route one request; returns the worker's Future.

        With request tracing enabled the router is where the trace ID
        is born (or adopted from the caller): the chosen worker's
        ``submit(feed, trace_id=...)`` joins the same trace, and once
        the worker has opened its span buffer the routing decision
        lands in it as a ``route`` span — a degraded-fleet request
        shows WHICH worker it was pinned to."""
        from paddle_tpu import observability as obs

        rt = obs.reqtrace
        if not rt.enabled():
            return self._pick().submit(feed)
        trace_id = trace_id or rt.new_trace_id()
        t0_us = rt.now_us()
        w = self._pick()
        fut = w.submit(feed, trace_id=trace_id)
        with self._lock:
            try:
                widx = self.workers.index(w)
            except ValueError:
                widx = -1
            n = len(self.workers)
        rt.add_span_by_id(trace_id, "route", t0_us,
                          rt.now_us() - t0_us, worker=widx, fleet=n,
                          burning=bool(w.burning()))
        return fut

    def _pick(self):
        with self._lock:
            workers = list(self.workers)
            self._rr += 1
            offset = self._rr
        if not workers:
            raise RuntimeError("FleetRouter has no workers (start() it)")
        n = len(workers)
        order = [workers[(offset + k) % n] for k in range(n)]
        alive = [w for w in order if w.alive()]
        if not alive:
            raise RuntimeError("FleetRouter: no live workers in a fleet "
                               "of %d" % n)
        # prefer workers not burning their SLO budget; if everyone is
        # burning, degraded service still beats dropping the request
        for w in alive:
            if not w.burning():
                return w
        return alive[0]

    # -- scaling ---------------------------------------------------------
    def maybe_scale(self, now=None):
        """One scaling decision; returns +1 (scaled out), -1 (scaled
        in), or 0. ``now`` defaults to the router's clock and is passed
        through to the workers' burn-rate windows so tests can drive a
        synthetic timeline."""
        from paddle_tpu import observability as obs

        now = self.clock() if now is None else now
        with self._lock:
            workers = list(self.workers)
            last = self._last_scale
        if not workers:
            return 0
        in_cooldown = (last is not None
                       and (now - last) < self.cooldown_s)
        fast = [w for w in workers if w.fast_burning(now=now)]
        if fast:
            if in_cooldown or len(workers) >= self.max_workers:
                return 0
            trigger = fast[0]
            snap_fn = getattr(trigger, "burn_snapshot", None)
            self.last_scale_out_burn = snap_fn(now=now) if snap_fn \
                else None
            size = self._add(self._build_worker())
            with self._lock:
                self._last_scale = now
            self.scale_outs += 1
            obs.inc("fleet.scale_out")
            obs.event("health.fleet_scaled", direction="out",
                      workers=size, spawn_ms=round(self.last_spawn_ms
                                                   or 0.0, 1),
                      burn=self.last_scale_out_burn)
            return 1
        if (len(workers) > self.min_workers and not in_cooldown
                and all(w.slow_recovered(now=now) for w in workers)):
            with self._lock:
                if len(self.workers) <= self.min_workers:
                    return 0
                w = self.workers.pop()
                size = len(self.workers)
                self._last_scale = now
            w.stop()                     # drains its queue first
            self.scale_ins += 1
            obs.inc("fleet.scale_in")
            obs.set_gauge("fleet.workers", size)
            obs.event("health.fleet_scaled", direction="in",
                      workers=size)
            return -1
        return 0

    def stats(self):
        return {"workers": self.n_workers, "scale_outs": self.scale_outs,
                "scale_ins": self.scale_ins,
                "last_spawn_ms": self.last_spawn_ms,
                "last_scale_out_burn": self.last_scale_out_burn}

    def health(self):
        """Fleet-level readiness: per-worker snapshots plus the verdict
        a load balancer wants (any live worker = routable)."""
        with self._lock:
            workers = list(self.workers)
        snaps = [w.health() for w in workers]
        return {"workers": len(workers),
                "healthy": any(s.get("worker_alive") for s in snaps),
                "scale_outs": self.scale_outs,
                "scale_ins": self.scale_ins,
                "per_worker": snaps}
