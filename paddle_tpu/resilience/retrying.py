"""Shared retry policy: capped exponential backoff + jitter under an
overall deadline (reference: the gRPC channel's reconnect backoff the
C++ RPC stack leans on — operators/distributed/grpc/grpc_client.cc
retries through the completion queue with FLAGS_rpc_deadline bounding
the total wait). Every transient-failure loop in the repo routes
through ``retry_call`` so backoff behaviour is one tested policy, not
N hand-rolled sleep loops: the pserver client connect path
(distributed/ps.py), the checkpoint background writer (checkpoint.py),
and the supervised launcher's gang restarts (distributed/launch.py).

Determinism: jitter comes from a ``random.Random(seed)`` stream owned
by the ``Backoff`` instance, so a seeded schedule replays exactly —
the property the fault-injection tests assert bounds on.
"""

import random
import time

__all__ = ["Backoff", "DeadlineExceeded", "RetriesExhausted", "retry_call"]


class DeadlineExceeded(OSError):
    """The overall deadline expired before an attempt succeeded; chains
    the last attempt's error as ``__cause__``."""


class RetriesExhausted(OSError):
    """The attempt budget ran out; chains the last attempt's error."""


class Backoff:
    """Capped exponential backoff with bounded jitter.

    Attempt ``k`` (0-based) sleeps ``d * (1 - jitter * u)`` where
    ``d = min(cap, base * factor**k)`` and ``u`` is uniform in [0, 1) —
    i.e. every delay lands in ``(d * (1 - jitter), d]``. Jittering
    DOWN from the deterministic envelope keeps the worst-case total
    wait computable while still de-synchronizing a gang of restarting
    workers (the thundering-herd property exponential backoff exists
    for).
    """

    def __init__(self, base=0.05, factor=2.0, cap=5.0, jitter=0.5,
                 seed=None):
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1], got %r" % jitter)
        self.base = float(base)
        self.factor = float(factor)
        self.cap = float(cap)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)

    def envelope(self, attempt):
        """The deterministic (jitter-free) delay for ``attempt``."""
        return min(self.cap, self.base * self.factor ** attempt)

    def delay(self, attempt):
        """The jittered delay for ``attempt`` (consumes the rng)."""
        d = self.envelope(attempt)
        if not self.jitter:
            return d
        return d * (1.0 - self.jitter * self._rng.random())


def retry_call(fn, *args, retry_on=(OSError,), attempts=None,
               deadline=None, backoff=None, on_retry=None,
               sleep=time.sleep, clock=time.monotonic, **kwargs):
    """Call ``fn(*args, **kwargs)`` until it succeeds.

    ``retry_on``    exception types that trigger a retry; anything else
                    propagates immediately.
    ``attempts``    total call budget (None = unbounded, deadline-only).
    ``deadline``    overall wall-clock budget in seconds measured from
                    entry (None = unbounded). The pre-retry sleep is
                    clipped to the remaining budget, and a retry whose
                    budget is exhausted raises ``DeadlineExceeded``
                    chaining the last error.
    ``backoff``     a ``Backoff`` (default: Backoff()).
    ``on_retry``    callback ``(exc, attempt, delay)`` invoked before
                    each sleep — the observability hook.
    """
    if attempts is None and deadline is None:
        raise ValueError("retry_call needs attempts and/or deadline — an "
                         "unbounded retry loop is a hang, not a policy")
    backoff = backoff if backoff is not None else Backoff()
    start = clock()
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as e:  # noqa: PERF203 - the whole point
            attempt += 1
            if attempts is not None and attempt >= attempts:
                raise RetriesExhausted(
                    "giving up after %d attempt(s): %s" % (attempt, e)
                ) from e
            delay = backoff.delay(attempt - 1)
            if deadline is not None:
                remaining = deadline - (clock() - start)
                if remaining <= 0:
                    raise DeadlineExceeded(
                        "deadline (%.1fs) exceeded after %d attempt(s): %s"
                        % (deadline, attempt, e)) from e
                delay = min(delay, remaining)
            if on_retry is not None:
                on_retry(e, attempt, delay)
            sleep(delay)
