"""Deterministic fault injection at the engine seams.

Large-pod fault tolerance is only trustworthy if every recovery path is
exercisable WITHOUT real hardware faults (the discipline TensorFlow's
fault-tolerance design demands and MLPerf-scale pod runs assume —
PAPERS.md). This module plants named **fault points** at the seams —
compile (engine cache miss), step run, checkpoint write, worker
liveness — and a schedule parsed from ``PADDLE_TPU_FAULT_SPEC`` decides
which hit of which point fires, on which rank, in which incarnation of
a supervised job. Everything is counter-driven: the same spec against
the same program replays the same faults.

Spec grammar (';'-separated entries)::

    spec  := entry (';' entry)*
    entry := point ['@' cond (':' cond)*]
    cond  := 'step' N   fire when the point's step (or hit count when
                        the seam passes none) equals N
           | N          shorthand for stepN
           | 'rank' N   only on worker rank N (PADDLE_TRAINER_ID)
           | 'restart' N  only in gang incarnation N (the supervisor
                          sets PADDLE_TPU_RESTART_COUNT; default 0, so
                          by default a fault does NOT re-fire after the
                          supervisor restarts the gang)
           | 'x' N      fire N times (default 1)
           | 'dev' N    payload parameter, not a match condition: which
                        addressable replica shard a ``bitflip`` corrupts
                        under a mesh (default 0; ignored elsewhere)

Examples: ``step_nan@7`` — poison the 7th step's outputs with NaN;
``worker_kill@rank1:step12`` — rank 1 hard-exits at step 12;
``compile@1;ckpt_write@20`` — the first compile and the step-20
checkpoint write each fail once (both absorbed by their retry paths).

Registered points and what firing does:

    step_nan     returns True to the engine, which multiplies the
                 step's float outputs by NaN — the real nan/inf guard
                 then trips exactly as a numeric blow-up would
    step_fail    raises InjectedFault out of the step
    compile      raises InjectedFault from the cache-miss build
    ckpt_write   raises InjectedFault inside the checkpoint writer's
                 write attempt (absorbed by its retry; enough
                 repetitions fail the save)
    worker_kill  hard process exit with KILLED_EXIT_CODE — no cleanup,
                 no atexit: the closest a test gets to SIGKILL/preemption
    worker_hang  sleep forever WITHOUT exiting: the step loop wedges
                 while daemon threads (the health heartbeat) keep
                 running — a deadlocked collective's exact signature.
                 Only the supervisor's heartbeat watchdog
                 (observability/health.py) can clear it; restart-gated
                 like worker_kill so the respawned gang does not re-hang
    worker_loss  hard process exit with LOST_EXIT_CODE — a PERMANENT
                 loss (dead host, failed VM): restarting the same rank
                 is pointless, so the supervisor shrinks the gang to
                 the survivors (distributed/launch.py --max-shrinks)
                 instead of burning the restart budget
    disk_fail    returns True to the caller, which poisons its LOCAL
                 checkpoint root (the ResilientDriver rmtree-s it) —
                 the dead-local-disk scenario checkpoint quorum restore
                 recovers from via a peer root's replica
    bitflip      returns the fired entry to the engine seam, which flips
                 ONE mantissa bit of a stored updated param
                 (resilience/sentinel.py apply_bitflip) — silent data
                 corruption: no exception, no NaN, nothing the nan/inf
                 guard can see. Only the PADDLE_TPU_SDC sentinel's
                 digest/replica/replay machinery catches it; with the
                 sentinel off it corrupts undetected BY DESIGN. Under a
                 mesh the flip lands on replica shard ``dev N``. An
                 ``x1`` entry is a transient (the sentinel's bit-exact
                 replay comes back clean); ``xN`` keeps re-firing at the
                 replay seam — a persistently flaky core, which the
                 replay vote blames
    preempt      returns the fired entry to the ResilientDriver's step
                 loop, which treats it exactly like SIGTERM: drain the
                 dispatch window, blocking checkpoint, exit
                 PREEMPT_EXIT_CODE — the supervisor restarts the gang
                 WITHOUT spending restart budget (preemption is
                 scheduled capacity loss, not a fault)
"""

import os
import time

from paddle_tpu import flags

__all__ = ["InjectedFault", "FaultEntry", "FaultSchedule", "KILLED_EXIT_CODE",
           "LOST_EXIT_CODE", "PREEMPT_EXIT_CODE", "active", "fault_point",
           "parse_fault_spec", "random_spec", "reset"]

KILLED_EXIT_CODE = 43
#: a PERMANENTLY lost worker (dead host): the supervisor must shrink
#: the gang over the survivors, not respawn this rank
LOST_EXIT_CODE = 45
#: a GRACEFULLY preempted worker (SIGTERM / scheduled eviction): it
#: drained its window and checkpointed before exiting, so the
#: supervisor restarts the gang without spending restart budget
PREEMPT_EXIT_CODE = 46

#: points that RETURN their fired entry (truthy) instead of raising —
#: the caller applies the corruption itself (the engine owns the arrays
#: to poison, the driver owns the checkpoint root to destroy / the
#: preemption protocol to run)
POISON_POINTS = frozenset(["step_nan", "disk_fail", "bitflip", "preempt"])

KNOWN_POINTS = frozenset(
    ["step_nan", "step_fail", "compile", "ckpt_write", "worker_kill",
     "worker_hang", "worker_loss", "disk_fail", "bitflip", "preempt"])


class InjectedFault(RuntimeError):
    """A fault-injection entry fired at a raising fault point."""

    def __init__(self, point, step=None):
        self.point = point
        self.step = step
        super().__init__(
            "injected fault at point %r (step %s)" % (point, step))


class FaultEntry:
    def __init__(self, point, step=None, rank=None, restart=None, repeat=1,
                 dev=None):
        self.point = point
        self.step = step
        self.rank = rank
        self.restart = 0 if restart is None else restart
        self.repeat = repeat
        # payload, not a match condition: which replica shard a bitflip
        # corrupts under a mesh
        self.dev = 0 if dev is None else dev
        self.fired = 0

    def matches(self, step, rank, restart):
        if self.fired >= self.repeat:
            return False
        if self.rank is not None and rank != self.rank:
            return False
        if restart != self.restart:
            return False
        return self.step is None or step == self.step

    def __repr__(self):
        conds = []
        if self.rank is not None:
            conds.append("rank%d" % self.rank)
        if self.step is not None:
            conds.append("step%d" % self.step)
        if self.restart:
            conds.append("restart%d" % self.restart)
        if self.repeat != 1:
            conds.append("x%d" % self.repeat)
        if self.dev:
            conds.append("dev%d" % self.dev)
        return self.point + ("@" + ":".join(conds) if conds else "")


def parse_fault_spec(spec):
    """``spec`` string -> [FaultEntry]; raises ValueError with the
    offending entry named on any grammar violation."""
    entries = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        point, _, tail = raw.partition("@")
        point = point.strip()
        if point not in KNOWN_POINTS:
            raise ValueError(
                "unknown fault point %r in %r (known: %s)"
                % (point, raw, sorted(KNOWN_POINTS)))
        kw = {}
        for cond in (tail.split(":") if tail else []):
            cond = cond.strip()
            for prefix, key in (("step", "step"), ("rank", "rank"),
                                ("restart", "restart"), ("dev", "dev"),
                                ("x", "repeat")):
                if cond.startswith(prefix) and cond[len(prefix):].isdigit():
                    kw[key] = int(cond[len(prefix):])
                    break
            else:
                if cond.isdigit():           # bare N == stepN
                    kw["step"] = int(cond)
                else:
                    raise ValueError(
                        "bad fault condition %r in %r" % (cond, raw))
        entries.append(FaultEntry(point, **kw))
    return entries


def random_spec(seed, n_steps, nproc=1, kinds=("worker_kill", "step_nan")):
    """A seeded random-but-reproducible chaos schedule: one entry per
    kind, each at a random step in the middle 80% of the run (early
    enough to matter, late enough that a checkpoint exists), kills
    pinned to a random rank. Same seed -> same spec (tools/chaos_run)."""
    import random as _random

    rng = _random.Random(seed)
    lo, hi = max(1, n_steps // 10), max(2, (9 * n_steps) // 10)
    parts = []
    for kind in kinds:
        conds = ["step%d" % rng.randint(lo, hi)]
        if kind in ("worker_kill", "worker_hang", "worker_loss", "preempt",
                    "bitflip"):
            # liveness/silent-corruption kinds pin to ONE rank so the
            # rest of the gang observes the event instead of sharing it
            conds.insert(0, "rank%d" % rng.randrange(nproc))
        if kind == "bitflip":
            # coin-flip transient (x1: the replay comes back clean) vs
            # persistent (the replay vote must blame the core)
            conds.append("x%d" % rng.choice((1, 9)))
        parts.append(kind + "@" + ":".join(conds))
    return ";".join(parts)


class FaultSchedule:
    """Parsed spec + per-point hit counters. Rank comes from
    PADDLE_TRAINER_ID, incarnation from PADDLE_TPU_RESTART_COUNT (both
    read at construction — the launcher sets them per worker spawn)."""

    def __init__(self, spec, rank=None, restart=None):
        self.spec = spec
        self.entries = parse_fault_spec(spec)
        self.rank = (int(os.environ.get("PADDLE_TRAINER_ID", "0"))
                     if rank is None else int(rank))
        self.restart = (int(os.environ.get("PADDLE_TPU_RESTART_COUNT", "0"))
                        if restart is None else int(restart))
        self._hits = {}

    def check(self, point, step=None):
        """Record one hit of ``point``; return the FaultEntry that fires
        now, or None. With no explicit ``step`` from the seam the
        point's own hit count (1-based) stands in for it."""
        hits = self._hits.get(point, 0) + 1
        self._hits[point] = hits
        eff = hits if step is None else step
        for e in self.entries:
            if e.point == point and e.matches(eff, self.rank, self.restart):
                e.fired += 1
                return e
        return None


_schedule = None


def _get_schedule(spec):
    global _schedule
    if _schedule is None or _schedule.spec != spec:
        _schedule = FaultSchedule(spec)
    return _schedule


def reset():
    """Drop the cached schedule (test isolation; hit counters restart)."""
    global _schedule
    _schedule = None


def active():
    """True when a fault spec is configured — the one-read fast gate the
    engine checks before paying any schedule work."""
    return bool(flags.get_flag("fault_spec"))


def fault_point(name, step=None):
    """Declare one hit of fault point ``name``. Returns False when no
    entry fires; returns the fired FaultEntry (truthy) for poison-style
    points — callers that only need a boolean keep working, the bitflip
    seam reads the entry's ``dev``/``fired`` payload; raises
    InjectedFault for failure-style points; never returns for
    worker_kill."""
    spec = flags.get_flag("fault_spec")
    if not spec:
        return False
    entry = _get_schedule(spec).check(name, step)
    if entry is None:
        return False
    from paddle_tpu import observability as obs

    obs.inc("faultinject.fired")
    obs.inc("faultinject.%s.fired" % name)
    obs.event("faultinject", point=name, step=step, entry=repr(entry))
    if name in ("worker_kill", "worker_loss"):
        # flush telemetry, then die the way a preempted worker dies:
        # immediately, skipping atexit/finally (os._exit) — siblings see
        # a vanished peer, the supervisor sees a non-zero exit. A
        # worker_loss exits with the PERMANENT code: this host is never
        # coming back, so the supervisor shrinks instead of respawning
        try:
            obs.flush_sink()
        except Exception:
            pass
        os._exit(KILLED_EXIT_CODE if name == "worker_kill"
                 else LOST_EXIT_CODE)
    if name == "worker_hang":
        # wedge the step loop forever WITHOUT exiting: the heartbeat
        # daemon keeps beating with a frozen step counter — exactly the
        # hung signature the supervisor's HealthMonitor must catch,
        # since no exit code will ever arrive
        try:
            obs.flush_sink()
        except Exception:
            pass
        while True:
            time.sleep(60.0)
    if name in POISON_POINTS:
        return entry
    raise InjectedFault(name, step)
