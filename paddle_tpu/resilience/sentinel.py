"""Silent-data-corruption (SDC) sentinel: in-graph step digests, replica
voting, deterministic re-execution, and device quarantine.

The resilience ladder below this layer handles failures that announce
themselves — crashes, hangs, lost workers. The sentinel catches the one
that does not: a flaky core returning a *wrong number*. Gated by the
``PADDLE_TPU_SDC`` flag, it works in three tiers:

1. **In-graph digest** — the engine's cache-miss seam fuses
   :func:`graph_digest` over the step's gradients and updated params into
   the jitted executable, returned as one extra ``uint32[4]`` fetch:
   ``[abs_sum_bits, nonfinite_count, checksum, tensor_count]``. The
   checksum is an additive-mod-2**32 sum of the float32 bit patterns —
   associative and order-independent, so the same values digest to the
   same word whether computed fused in-graph or eagerly at the seam.
2. **Detection at retire** — the seam eagerly recomputes the digest over
   the materialized seam arrays and, under a dp mesh, per-device shard
   checksums of the replicated state. A mismatch (exact tier), a replica
   disagreement (vote tier), or an abs-sum outside the seeded EWMA band
   (statistical tier) raises :class:`SDCSuspect` carrying the ORIGINAL
   step — dispatched at enqueue, checked at retire, composing with the
   dispatch window exactly like the deferred nan/inf verdict.
3. **Replay vote + quarantine** — :meth:`StepSentinel.recover` re-invokes
   the retained executable on the retained inputs (rng is
   ``(seed, run_counter)``-derived in-graph, so replay is bit-exact by
   construction) and votes: clean replay → transient (adopt the replayed
   state, continue); deterministic reproduction of a band-only anomaly →
   genuine data (widen the band, continue); still corrupt / same minority
   device → blamed. A blamed device feeds the elastic lost-device
   registry and the supervisor's existing shrink path.

Only the abs-sum component feeds the EWMA band; it is NEVER compared
bitwise (XLA may re-associate the float reduction between fusion
contexts). Exact comparisons use the nonfinite/checksum/count words only.
"""

import collections

import numpy as np

from paddle_tpu import flags
from paddle_tpu import observability as obs

__all__ = [
    "SDCSuspect", "SDCBlamed", "EWMABand", "SentinelProbe", "StepSentinel",
    "graph_digest", "digest_fields", "digests_match", "replica_checksums",
    "apply_bitflip",
]


class SDCSuspect(RuntimeError):
    """A step's digest failed verification at retire. Carries the ORIGINAL
    engine step (run-counter value) so a deferred verdict names the step
    that computed the bad number, not the step that surfaced it."""

    def __init__(self, step, reason, device=None, detail=""):
        self.step = int(step)
        self.reason = str(reason)
        self.device = device
        super().__init__(
            "sdc_suspect: step %d reason=%s%s%s" % (
                self.step, self.reason,
                "" if device is None else " device=%s" % device,
                (" " + detail) if detail else ""))


class SDCBlamed(RuntimeError):
    """Replay reproduced the corruption on the same device: the hardware
    is blamed. Raised to the caller when in-process quarantine is not
    possible (no shrinkable mesh); the chaos worker maps it to the
    lost-device exit code so the supervisor takes the gang-shrink path."""

    def __init__(self, step, device=None):
        self.step = int(step)
        self.device = device
        super().__init__(
            "sdc_blamed: step %d device=%s" % (self.step, device))


# ---------------------------------------------------------------------------
# digest
# ---------------------------------------------------------------------------

def _digest_terms(x):
    """(abs_sum f32, nonfinite u32, checksum u32) for one float tensor, or
    None for non-float values. Works traced (inside jit) and eagerly."""
    import jax.numpy as jnp
    from jax import lax

    dt = getattr(x, "dtype", None)
    if dt is None or not jnp.issubdtype(jnp.dtype(dt), jnp.floating):
        return None
    y = jnp.asarray(x).astype(jnp.float32)
    # abs-sum is deliberately UNMASKED (no where(finite, ...) pass): a
    # nonfinite tensor poisons the band word, but the nonfinite count
    # and the engine's own nan/inf guard both flag that step anyway,
    # and dropping the select halves the digest's elementwise work
    abs_sum = jnp.sum(jnp.abs(y), dtype=jnp.float32)
    nonfinite = jnp.sum(~jnp.isfinite(y), dtype=jnp.uint32)
    bits = lax.bitcast_convert_type(y, jnp.uint32)
    checksum = jnp.sum(bits, dtype=jnp.uint32)  # wraps mod 2**32: order-free
    return abs_sum, nonfinite, checksum


def graph_digest(values, exact_start=0):
    """uint32[4] digest over the float tensors of ``values`` (non-float
    entries are skipped).

    The band words (abs-sum, nonfinite count) cover ALL of ``values``;
    the exact words (checksum, tensor count) cover ``values[exact_start:]``
    only. The fused in-graph call passes gradients + updated state with
    ``exact_start`` at the state boundary, so the gradients feed the
    statistical band WITHOUT ever being materialized as jit outputs,
    while the checksum covers exactly the arrays that cross the host
    seam — the only ones the seam recompute can (and needs to) verify."""
    import jax.numpy as jnp
    from jax import lax

    abs_sum = jnp.float32(0.0)
    nonfinite = jnp.uint32(0)
    checksum = jnp.uint32(0)
    count = 0
    for i, x in enumerate(values):
        t = _digest_terms(x)
        if t is None:
            continue
        abs_sum = abs_sum + t[0]
        nonfinite = nonfinite + t[1]
        if i >= exact_start:
            checksum = checksum + t[2]
            count += 1
    return jnp.stack([lax.bitcast_convert_type(abs_sum, jnp.uint32),
                      nonfinite, checksum, jnp.uint32(count)])


def _exact_digest(values):
    """Exact words only — [0, 0, checksum, count] — over every float
    tensor of ``values``. The seam recompute is compared on [2:] alone
    (digests_match), so recomputing the band words would be pure waste:
    this is one u32 pass per tensor instead of four float passes."""
    import jax.numpy as jnp
    from jax import lax

    checksum = jnp.uint32(0)
    count = 0
    for x in values:
        dt = getattr(x, "dtype", None)
        if dt is None or not jnp.issubdtype(jnp.dtype(dt), jnp.floating):
            continue
        y = jnp.asarray(x).astype(jnp.float32)
        bits = lax.bitcast_convert_type(y, jnp.uint32)
        checksum = checksum + jnp.sum(bits, dtype=jnp.uint32)
        count += 1
    return jnp.stack([jnp.uint32(0), jnp.uint32(0), checksum,
                      jnp.uint32(count)])


_seam_digest_jit = None


def seam_digest(values):
    """:func:`_exact_digest`, jit-compiled, for the host-side seam
    recompute: one dispatch per step instead of ~6 eager ops per tensor
    (which costs more than the training step on small models). jax.jit's
    cache keys on the list's shapes/dtypes, so each compiled block pays
    one trace and then near-zero dispatch. The checksum word is bit-
    identical to the fused one by construction: both are order-free
    uint32 sums of the same f32 bit patterns."""
    global _seam_digest_jit
    import jax

    if _seam_digest_jit is None:
        _seam_digest_jit = jax.jit(_exact_digest)
    return _seam_digest_jit(list(values))


def digest_fields(digest):
    """(abs_sum float, nonfinite int, checksum int, count int) from a
    materialized uint32[4] digest."""
    d = np.asarray(digest, dtype=np.uint32).reshape(-1)
    return (float(d[0:1].view(np.float32)[0]),
            int(d[1]), int(d[2]), int(d[3]))


def digests_match(a, b):
    """Exact comparison over the seam-verifiable words only (checksum,
    count) — NEVER the float abs-sum (reduction order may legally differ
    between fusion contexts) and not the nonfinite count (the fused word
    also counts gradients, which the seam recompute never sees)."""
    fa, fb = digest_fields(a), digest_fields(b)
    return fa[2:] == fb[2:]


def replica_checksums(values):
    """Per-device (nonfinite, checksum) pairs over the fully-replicated
    float arrays of ``values``. Each shard is digested ON its own device
    (``shard.data`` is device-local), so a corrupt replica's checksum
    carries its provenance. Returns {} off-mesh or with < 2 replicas."""
    import jax
    import jax.numpy as jnp

    per_dev = {}
    for a in values:
        if not isinstance(a, jax.Array):
            continue
        dt = getattr(a, "dtype", None)
        if dt is None or not jnp.issubdtype(jnp.dtype(dt), jnp.floating):
            continue
        try:
            shards = a.addressable_shards
        except Exception:
            continue
        if len(shards) < 2:
            continue
        if any(s.data.shape != a.shape for s in shards):
            continue  # sharded, not replicated: no per-device redundancy
        for s in shards:
            t = _digest_terms(s.data)
            per_dev.setdefault(int(s.device.id), []).append(
                (t[1], t[2]))
    return per_dev


def _resolve_replicas(per_dev):
    """Materialize per-device checksum lists into {dev_id: (nf, ck)}."""
    out = {}
    for dev, terms in per_dev.items():
        nf, ck = 0, 0
        for t_nf, t_ck in terms:
            nf += int(np.asarray(t_nf))
            ck = (ck + int(np.asarray(t_ck))) & 0xFFFFFFFF
        out[dev] = (nf, ck)
    return out


def _minority_device(resolved):
    """The device whose (nonfinite, checksum) tuple disagrees with the
    majority, or None when all replicas agree / there is no majority."""
    if len(resolved) < 2:
        return None
    votes = collections.Counter(resolved.values())
    value, n = votes.most_common(1)[0]
    if n <= len(resolved) - n:
        return None  # no strict majority: cannot assign blame
    bad = sorted(d for d, v in resolved.items() if v != value)
    return bad[0] if bad else None


# ---------------------------------------------------------------------------
# EWMA band (statistical tier)
# ---------------------------------------------------------------------------

class EWMABand:
    """Seeded EWMA band over the digest abs-sum. Flags only GROSS
    deviations (``sdc_band`` sigmas plus a 25% relative floor) — the exact
    and replica tiers own precision detection; this tier exists to catch
    large-magnitude corruption on a single device with no replica."""

    def __init__(self, k=None, warmup=None, alpha=0.2):
        self.k = float(flags.get_flag("sdc_band")) if k is None else float(k)
        self.warmup = (int(flags.get_flag("sdc_warmup"))
                       if warmup is None else int(warmup))
        self.alpha = float(alpha)
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def anomalous(self, x):
        if self.n < self.warmup:
            return False
        sd = max(self.var ** 0.5, 1e-12)
        return abs(x - self.mean) > self.k * sd + 0.25 * abs(self.mean)

    def update(self, x):
        if not np.isfinite(x):
            # the abs-sum word is unmasked: a nan/inf step (caught by
            # the finite guard and rolled back) must not poison the band
            return
        self.n += 1
        if self.n == 1:
            self.mean = float(x)
            return
        d = float(x) - self.mean
        self.mean += self.alpha * d
        self.var = (1.0 - self.alpha) * (self.var + self.alpha * d * d)


# ---------------------------------------------------------------------------
# bitflip fault (used by the engine seam when faultinject arms `bitflip`)
# ---------------------------------------------------------------------------

def _is_float_array(v):
    dt = getattr(v, "dtype", None)
    return dt is not None and np.issubdtype(np.dtype(dt), np.floating)


def apply_bitflip(state_out, names, entry):
    """Flip one mantissa bit of the first float32 state tensor (the
    stored updated param). The flipped bit varies with the entry's fired
    count so a persistent fault corrupts replays DIFFERENTLY — exactly how
    a flaky core behaves, and what the replay vote keys on. Under a mesh
    the flip lands on addressable shard ``entry.dev`` only, modeling a
    single bad device among replicas. Returns a new state_out list."""
    import jax

    idx = None
    for i, v in enumerate(state_out):
        if _is_float_array(v) and getattr(v, "size", 0) > 1 \
                and np.dtype(getattr(v, "dtype")) == np.float32:
            idx = i
            break
    if idx is None:
        return state_out

    fired = max(1, int(getattr(entry, "fired", 1)))
    bit = 8 + (fired - 1) % 15  # float32 mantissa region
    target = state_out[idx]
    name = names[idx] if idx < len(names) else "?"

    shards = getattr(target, "addressable_shards", None)
    if isinstance(target, jax.Array) and shards and len(shards) > 1 \
            and all(s.data.shape == target.shape for s in shards):
        dev = min(int(getattr(entry, "dev", 0)), len(shards) - 1)
        pieces = []
        for j, s in enumerate(shards):
            host = np.array(s.data, dtype=np.float32, copy=True)
            if j == dev:
                u = host.reshape(-1).view(np.uint32)
                u[0] ^= np.uint32(1 << bit)
            pieces.append(jax.device_put(host, s.device))
        flipped = jax.make_array_from_single_device_arrays(
            target.shape, target.sharding, pieces)
        where = "dev%d" % shards[dev].device.id
    else:
        host = np.array(target, dtype=np.float32, copy=True)
        u = host.reshape(-1).view(np.uint32)
        u[0] ^= np.uint32(1 << bit)
        flipped = host
        where = "local"

    obs.inc("sentinel.bitflips_injected")
    obs.event("sentinel.bitflip_injected", var=name, bit=bit, where=where)
    out = list(state_out)
    out[idx] = flipped
    return out


# ---------------------------------------------------------------------------
# probe + sentinel
# ---------------------------------------------------------------------------

_ReplayRecord = collections.namedtuple(
    "_ReplayRecord",
    ["step", "jitted", "args", "state_out_names", "digest",
     "user_fetches", "writeback", "scope", "mesh", "band"])


class SentinelProbe:
    """One step's deferred verdict: digests dispatched at enqueue,
    compared at retire. Mirrors FiniteProbe's lifecycle — `check()` is
    called either inline (sync path) or from the window's `_resolve`."""

    __slots__ = ("step", "sentinel", "digest", "recompute", "per_dev",
                 "band", "checked")

    def __init__(self, step, sentinel, digest, recompute, per_dev, band):
        self.step = step
        self.sentinel = sentinel
        self.digest = digest          # in-graph uint32[4] (device value)
        self.recompute = recompute    # eager uint32[4] over seam arrays
        self.per_dev = per_dev        # {dev_id: [(nf, ck), ...]} or {}
        self.band = band
        self.checked = False

    def check(self):
        if self.checked:
            return
        self.checked = True
        obs.inc("sentinel.checks")

        fused = digest_fields(self.digest)
        seam = digest_fields(self.recompute)
        if fused[2:] != seam[2:]:
            self._suspect("mismatch",
                          detail="fused=%s seam=%s" % (fused[2:], seam[2:]))

        if self.per_dev:
            resolved = _resolve_replicas(self.per_dev)
            bad = _minority_device(resolved)
            if bad is not None:
                self._suspect("replica", device=bad,
                              detail="votes=%s" % sorted(resolved.items()))

        if self.band is not None:
            if self.band.anomalous(fused[0]):
                # Do NOT fold the suspect value into the band: a genuine
                # verdict re-admits it after the replay vote.
                self._suspect("band",
                              detail="abs=%.6g mean=%.6g" % (fused[0],
                                                             self.band.mean))
            self.band.update(fused[0])

    def _suspect(self, reason, device=None, detail=""):
        obs.inc("sentinel.suspects")
        obs.event("sentinel.suspect", step=self.step, reason=reason,
                  device=-1 if device is None else int(device))
        raise SDCSuspect(self.step, reason, device=device, detail=detail)


class StepSentinel:
    """Per-engine sentinel state: retained replay records keyed by engine
    step, plus the observe/recover entry points the engine seam calls."""

    def __init__(self):
        self.retained = collections.OrderedDict()

    # -- enqueue-side ------------------------------------------------------

    def observe(self, step, compiled, digest, state_out,
                user_fetches, args, writeback, scope, mesh):
        """Dispatch the seam recompute (one jitted digest over the
        updated state — the arrays seam corruption can actually touch) +
        replica checksums, and retain a replay record. Returns the probe
        to check at retire."""
        obs.inc("sentinel.steps")
        recompute = seam_digest(list(state_out))
        per_dev = replica_checksums(state_out) if mesh is not None else {}
        band = getattr(compiled, "sdc_band", None)

        rec = _ReplayRecord(
            step=step, jitted=compiled.jitted, args=args,
            state_out_names=tuple(compiled.block_program.state_out_names),
            digest=digest, user_fetches=list(user_fetches),
            writeback=writeback, scope=scope, mesh=mesh, band=band)
        self.retained[step] = rec
        limit = max(2, int(flags.get_flag("sdc_retain")))
        while len(self.retained) > limit:
            self.retained.popitem(last=False)

        return SentinelProbe(step, self, digest, recompute, per_dev, band)

    # -- retire-side -------------------------------------------------------

    def recover(self, step, reason=None):
        """Deterministic re-execution + vote for a suspect step. Returns a
        verdict dict {kind: transient|genuine|blamed, fetches, device}.
        ``reason`` is the suspect's detection tier: only a ``band``
        suspect can be voted genuine (a real gradient spike reproduces
        bit-exactly AND verifies); exact/replica suspects prove seam
        corruption, so a clean replay means transient, a corrupt one
        means blamed. Raises KeyError when the replay record was evicted
        (caller falls back to checkpoint rollback)."""
        from paddle_tpu.resilience import faultinject

        rec = self.retained[step]
        obs.inc("sentinel.replays")

        fetches2, state_out2 = rec.jitted(*rec.args)
        fetches2 = list(fetches2)
        digest2 = fetches2.pop()
        user2 = fetches2

        # Re-arm the seam corruption exactly as the original run saw it:
        # an exhausted x1 entry will NOT re-fire (transient), a persistent
        # xN entry re-fires and corrupts the replay too.
        if faultinject.active():
            entry = faultinject.fault_point("bitflip", step=step)
            if entry:
                state_out2 = apply_bitflip(
                    list(state_out2), list(rec.state_out_names), entry)

        recompute2 = seam_digest(list(state_out2))
        per_dev2 = (replica_checksums(state_out2)
                    if rec.mesh is not None else {})

        f1 = digest_fields(rec.digest)      # original in-graph digest
        f2 = digest_fields(digest2)         # replayed in-graph digest
        r2 = digest_fields(recompute2)      # replayed seam digest
        resolved2 = _resolve_replicas(per_dev2)
        bad2 = _minority_device(resolved2)
        replay_clean = (f2[2:] == r2[2:]) and bad2 is None
        deterministic = f1[1:] == f2[1:]

        if replay_clean and deterministic and reason == "band":
            # The anomaly reproduces bit-exactly AND verifies: genuine
            # data (e.g. a real gradient spike), not corruption. Fold the
            # value into the band so it stops alarming.
            if rec.band is not None:
                rec.band.update(f1[0])
            verdict = "genuine"
            obs.inc("sentinel.genuine")
        elif replay_clean:
            verdict = "transient"
            obs.inc("sentinel.transient")
        else:
            verdict = "blamed"

        if verdict == "blamed":
            import jax
            device = bad2
            if device is None:
                device = int(jax.local_devices()[0].id)
            obs.inc("sentinel.blamed")
            import os
            obs.event("sentinel.blamed", step=step, device=int(device),
                      rank=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
            return {"kind": "blamed", "device": int(device),
                    "fetches": None}

        # transient/genuine: adopt the verified replayed state so the
        # driver resumes from a clean post-step scope (the original
        # in-scope state may be the corrupted one, or later window steps
        # may already have advanced it).
        if rec.writeback and rec.scope is not None:
            for name, val in zip(rec.state_out_names, state_out2):
                rec.scope.set(name, val)
        import jax
        fetches = [np.asarray(jax.device_get(v)) for v in user2]
        obs.event("sentinel." + verdict, step=step)
        return {"kind": verdict, "device": None, "fetches": fetches}

    # -- lifecycle ---------------------------------------------------------

    def discard(self):
        """Drop retained replay records (rollback / window discard: the
        retained donated-state references are no longer the live state)."""
        self.retained.clear()
