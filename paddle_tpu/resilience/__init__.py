"""paddle_tpu.resilience — the fault-tolerance layer that turns the
existing parts (async sharded ``CheckpointManager``, the nan/inf step
guard, per-worker telemetry sinks) into a system that survives worker
loss and numeric blow-ups (ISSUE 5; PAPERS.md: TensorFlow's
checkpoint/restore-centric fault-tolerance design, MLPerf-scale TPU-pod
preemption-as-routine).

Three pieces:

* ``retrying``    — one shared backoff/deadline/jitter policy
  (pserver connects, checkpoint writes, gang restarts);
* ``faultinject`` — deterministic named fault points at the engine
  seams, scheduled by ``PADDLE_TPU_FAULT_SPEC`` so every recovery path
  runs in CPU-only tests;
* ``driver``      — the rollback-on-fault step loop around
  ``Executor.run`` + a ``CheckpointManager``.

The supervised elastic launcher lives in ``distributed/launch.py``
(it IS the launcher, grown a supervisor) and reads
``PADDLE_TPU_MAX_RESTARTS`` / ``PADDLE_TPU_RECOVERY_CKPT``.
"""

from paddle_tpu.resilience import driver, faultinject, retrying  # noqa: F401
from paddle_tpu.resilience.driver import (  # noqa: F401
    FaultBudgetExceeded,
    ResilientDriver,
)
from paddle_tpu.resilience.faultinject import (  # noqa: F401
    InjectedFault,
    fault_point,
)
from paddle_tpu.resilience.retrying import (  # noqa: F401
    Backoff,
    DeadlineExceeded,
    RetriesExhausted,
    retry_call,
)

__all__ = [
    "Backoff", "DeadlineExceeded", "FaultBudgetExceeded", "InjectedFault",
    "ResilientDriver", "RetriesExhausted", "driver", "fault_point",
    "faultinject", "retry_call", "retrying",
]
