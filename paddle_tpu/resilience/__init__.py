"""paddle_tpu.resilience — the fault-tolerance layer that turns the
existing parts (async sharded ``CheckpointManager``, the nan/inf step
guard, per-worker telemetry sinks) into a system that survives worker
loss and numeric blow-ups (ISSUE 5; PAPERS.md: TensorFlow's
checkpoint/restore-centric fault-tolerance design, MLPerf-scale TPU-pod
preemption-as-routine).

Four pieces:

* ``retrying``    — one shared backoff/deadline/jitter policy
  (pserver connects, checkpoint writes, gang restarts);
* ``faultinject`` — deterministic named fault points at the engine
  seams, scheduled by ``PADDLE_TPU_FAULT_SPEC`` so every recovery path
  runs in CPU-only tests;
* ``driver``      — the rollback-on-fault step loop around
  ``Executor.run`` + a ``CheckpointManager``;
* ``elastic``     — acting on permanent loss WITHOUT losing the job:
  the lost-device registry ``dp=-1`` meshes re-plan over, the
  ``LOST_EXIT_CODE`` the supervisor's gang-shrink path keys on, and
  the SLO-burn-driven serving ``FleetRouter``;
* ``sentinel``    — the silent-data-corruption defense
  (``PADDLE_TPU_SDC``): in-graph step digests at the engine seam,
  replica voting under a dp mesh, deterministic re-execution of
  suspect steps, and device quarantine through the elastic registry.

The supervised elastic launcher lives in ``distributed/launch.py``
(it IS the launcher, grown a supervisor) and reads
``PADDLE_TPU_MAX_RESTARTS`` / ``PADDLE_TPU_MAX_SHRINKS`` /
``PADDLE_TPU_RECOVERY_CKPT``.
"""

from paddle_tpu.resilience import (  # noqa: F401
    driver,
    elastic,
    faultinject,
    retrying,
    sentinel,
)
from paddle_tpu.resilience.driver import (  # noqa: F401
    FaultBudgetExceeded,
    ResilientDriver,
)
from paddle_tpu.resilience.elastic import (  # noqa: F401
    FleetRouter,
    mark_device_lost,
    reset_lost,
    surviving_devices,
)
from paddle_tpu.resilience.faultinject import (  # noqa: F401
    LOST_EXIT_CODE,
    PREEMPT_EXIT_CODE,
    InjectedFault,
    fault_point,
)
from paddle_tpu.resilience.sentinel import (  # noqa: F401
    SDCBlamed,
    SDCSuspect,
    StepSentinel,
)
from paddle_tpu.resilience.retrying import (  # noqa: F401
    Backoff,
    DeadlineExceeded,
    RetriesExhausted,
    retry_call,
)

__all__ = [
    "Backoff", "DeadlineExceeded", "FaultBudgetExceeded", "FleetRouter",
    "InjectedFault", "LOST_EXIT_CODE", "PREEMPT_EXIT_CODE",
    "ResilientDriver", "RetriesExhausted", "SDCBlamed", "SDCSuspect",
    "StepSentinel", "driver", "elastic", "fault_point", "faultinject",
    "mark_device_lost", "reset_lost", "retry_call", "retrying",
    "sentinel", "surviving_devices",
]
